"""Canonical prefix chain hashes — the fleet-routable view of the
block-paged prefix index (ISSUE 19).

Pure stdlib ON PURPOSE (jax-free by the graftlint contract, like
fleet/ and obs/slo.py): the fleet router loads this module by FILE
PATH to hash an incoming prompt's chain keys, and it must keep doing
so on hosts where the replicas' jax is the thing that died.

The serve-side prefix index (serve/slots.py BlockAllocator) keys full
blocks on the recursive chain key ``(parent_key, tokens)`` — a block's
key encodes every token before it.  That structure cannot travel in a
heartbeat (it is a nest of tuples holding the raw tokens).  What CAN
travel is a short stable hash of the *cumulative token prefix* each
indexed block covers: block i of a prompt hashes
``prompt[0:(i + 1) * block_size]``.  Both sides of the fence compute
the same digest:

- a serve replica advertises ``hash_prefix()`` digests of its hottest
  indexed blocks (``BlockPool.hot_prefix_hashes``, ranked by refcount)
  in ``replica_state`` heartbeats;
- the router computes ``chain_hashes()`` of an incoming prompt and
  scores candidates by overlap (fleet/router.py policy
  ``prefix_affinity``).

The per-prompt chain mirrors ``BlockAllocator.match_prefix``'s cap:
only blocks fully contained in ``prompt[:-1]`` are useful (the last
prompt token is always re-fed to produce the first sampled token's
logits), so ``chain_hashes`` stops at ``(len(prompt) - 1) //
block_size`` full blocks.
"""

from __future__ import annotations

import zlib
from typing import List, Sequence

# Digest namespace version: bump if the hashing scheme ever changes so
# a mixed fleet's stale advertisements can never false-match.
_TAG = b"apex-prefix-v1:"


def hash_prefix(tokens: Sequence[int]) -> str:
    """Stable 8-hex-digit digest of one cumulative token prefix.

    crc32 over the decimal-rendered token ids — stdlib, byte-order
    free, and identical however the caller stores its tokens (list,
    tuple, numpy scalars that stringify as ints)."""
    payload = _TAG + ",".join(str(int(t)) for t in tokens).encode()
    return f"{zlib.crc32(payload) & 0xFFFFFFFF:08x}"


def chain_hashes(tokens: Sequence[int], block_size: int) -> List[str]:
    """The prompt's chain-key digests, one per USEFUL full block:
    entry i hashes ``tokens[0:(i + 1) * block_size]`` — exactly the
    cumulative prefix an indexed serve-side block at depth i covers.
    Capped at ``(len(tokens) - 1) // block_size`` (match_prefix re-feeds
    the last prompt token, so a block ending exactly at the prompt
    boundary is never shareable)."""
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    toks = [int(t) for t in tokens]
    n_blocks = max(len(toks) - 1, 0) // block_size
    return [hash_prefix(toks[:(i + 1) * block_size])
            for i in range(n_blocks)]


def overlap(prompt_hashes: Sequence[str],
            advertised: Sequence[str]) -> int:
    """Affinity score: the DEPTH of the advertised chain along the
    prompt — chain hashes are cumulative, so the score counts leading
    entries of ``prompt_hashes`` present in ``advertised`` and stops at
    the first miss (a replica holding block 3 but not block 2 of this
    prompt cannot actually serve block 3 from cache; counting it would
    overpromise)."""
    adv = set(advertised)
    depth = 0
    for h in prompt_hashes:
        if h not in adv:
            break
        depth += 1
    return depth
