"""Deficit-weighted round-robin admission over per-tenant lanes
(ISSUE 19).

Pure stdlib (jax-free by the graftlint contract).  The scheduler sits
BETWEEN the intake queue and the slot pool: the engine keeps
``RequestQueue`` as its arrival-gated intake (virtual-time gating,
shed_overflow, cancel-by-uid before admission all stay there), and —
when tenancy is armed — drains matured pops into per-tenant lanes,
then admits from ``next()`` instead of FIFO order.

Scheduling model (classic DWRR, single-pop API):

- One FIFO lane per tenant.  Requests carry ``tenant`` (unknown
  tenants auto-lane with default spec: weight 1, no budget, batch —
  a replica never drops a request because its spec list lagged).
- Lanes are grouped by SLO class; every ``interactive`` lane is
  offered the slot before any ``batch`` lane (the TTFT-critical
  preemption lane).  Within a class, a rotating cursor visits lanes
  in spec order; a lane that cannot serve accrues
  ``quantum * weight`` deficit per pass, and serves when its deficit
  covers the head's token cost (``len(prompt) + max_new_tokens``).
  A lane that empties forfeits its deficit (standard DRR — no
  hoarding credit while idle).
- Per-tenant token budgets debit at admission.  An over-budget head
  PARKS its lane (strict per-tenant FIFO: nothing behind it jumps);
  parked requests are never dropped by the scheduler — the engine
  finalizes them as ``rejected`` only once the intake is drained and
  they provably can never admit (budgets never replenish), via
  ``reject_overbudget_heads``.
- ``push_front`` re-credits both deficit and budget: the engine
  pushes a request back when the pool lacks blocks this step, and
  that must not burn the tenant's allowance.

Everything is integer/float arithmetic over deques — deterministic
under any host load, which is what makes the noisy_neighbor chaos
verdicts bit-reproducible.
"""

from __future__ import annotations

import importlib.util
import os
import sys
from collections import deque
from typing import Deque, Dict, List, Optional


def _load_tenants():
    """File-path sibling load: a package-relative import would put
    ``apex_example_tpu/__init__`` (and through it amp -> jax) under
    the contract BFS, so the lane specs load the way every other
    jax-free stratum borrows a sibling — by path.  Registered in
    sys.modules BEFORE exec: the dataclass machinery resolves
    ``cls.__module__`` through it."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tenants.py")
    spec = importlib.util.spec_from_file_location(
        "apex_sched_fair_tenants", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


_tenants_mod = _load_tenants()
DEFAULT_SPEC = _tenants_mod.DEFAULT_SPEC
TenantSpec = _tenants_mod.TenantSpec

# Deficit accrued per unserved pass, scaled by lane weight.  Small vs
# typical request cost so weights shape admission ORDER, not just
# long-run share.
DEFAULT_QUANTUM = 16

_CLASSES = ("interactive", "batch")


def request_cost(req) -> int:
    """Token cost a request charges its tenant: prompt plus the decode
    allowance.  Duck-typed — the scheduler never imports serve.queue
    (that would put a jax-adjacent edge under the contract BFS)."""
    return len(req.prompt) + int(req.max_new_tokens)


class FairScheduler:
    """DWRR admission over per-tenant lanes with token budgets."""

    def __init__(self, specs: Optional[Dict[str, TenantSpec]] = None,
                 quantum: int = DEFAULT_QUANTUM):
        if quantum < 1:
            raise ValueError(f"quantum must be >= 1, got {quantum}")
        self._specs: Dict[str, TenantSpec] = dict(specs or {})
        self._quantum = quantum
        self._order: List[str] = list(self._specs)
        self._lanes: Dict[str, Deque] = {n: deque() for n in self._order}
        self._deficit: Dict[str, float] = {n: 0.0 for n in self._order}
        self._cursor: Dict[str, int] = {c: 0 for c in _CLASSES}
        self.admitted_tokens: Dict[str, int] = {n: 0 for n in self._order}
        self.parked_peak: Dict[str, int] = {n: 0 for n in self._order}

    # -- tenant plumbing ------------------------------------------------

    def spec(self, name: str) -> TenantSpec:
        return self._specs.get(name, DEFAULT_SPEC)

    def _ensure_lane(self, name: str) -> None:
        if name not in self._lanes:
            self._specs.setdefault(
                name, TenantSpec(name=name))      # auto-lane defaults
            self._order.append(name)
            self._lanes[name] = deque()
            self._deficit[name] = 0.0
            self.admitted_tokens[name] = 0
            self.parked_peak[name] = 0

    def _budget_left(self, name: str) -> Optional[int]:
        budget = self.spec(name).budget
        if budget is None:
            return None
        return budget - self.admitted_tokens[name]

    def _parked(self, name: str) -> bool:
        lane = self._lanes[name]
        if not lane:
            return False
        left = self._budget_left(name)
        return left is not None and request_cost(lane[0]) > left

    # -- intake ---------------------------------------------------------

    def enqueue(self, req) -> None:
        tenant = getattr(req, "tenant", "default")
        self._ensure_lane(tenant)
        lane = self._lanes[tenant]
        prio = int(getattr(req, "priority", 0))
        if prio and any(int(getattr(r, "priority", 0)) < prio
                        for r in lane):
            # stable insert ahead of strictly-lower-priority entries
            items = list(lane)
            idx = next(i for i, r in enumerate(items)
                       if int(getattr(r, "priority", 0)) < prio)
            items.insert(idx, req)
            lane.clear()
            lane.extend(items)
        else:
            lane.append(req)
        if self._parked(tenant):
            self.parked_peak[tenant] = max(
                self.parked_peak[tenant], len(lane))

    def push_front(self, req) -> None:
        """Return an admitted-but-unplaced request to its lane head,
        refunding the budget debit and the deficit spend."""
        tenant = getattr(req, "tenant", "default")
        self._ensure_lane(tenant)
        cost = request_cost(req)
        self.admitted_tokens[tenant] -= cost
        self._deficit[tenant] += cost
        self._lanes[tenant].appendleft(req)

    def refund(self, req) -> None:
        """Reverse ``next()``'s budget debit WITHOUT requeueing — for a
        request the engine rejects as unservable at admission (it never
        consumed the tenant's allowance)."""
        tenant = getattr(req, "tenant", "default")
        self._ensure_lane(tenant)
        self.admitted_tokens[tenant] -= request_cost(req)

    # -- the DWRR pop ---------------------------------------------------

    def next(self):
        """The next admissible request under weighted fairness, or
        None when every lane is empty or budget-parked."""
        for cls in _CLASSES:
            req = self._next_in_class(cls)
            if req is not None:
                return req
        return None

    def _class_names(self, cls: str) -> List[str]:
        return [n for n in self._order
                if self.spec(n).slo_class == cls]

    def _next_in_class(self, cls: str):
        names = self._class_names(cls)
        if not names:
            return None

        def servable() -> bool:
            return any(self._lanes[n] and not self._parked(n)
                       for n in names)

        if not servable():
            return None
        # Each full rotation adds >= quantum to some nonempty lane's
        # deficit, so service is reached within cost/quantum rotations;
        # the cap is a pure backstop.
        max_spins = 4 * len(names) * (1 + max(
            request_cost(self._lanes[n][0]) // self._quantum
            for n in names if self._lanes[n]))
        spins = 0
        while spins < max_spins:
            spins += 1
            name = names[self._cursor[cls] % len(names)]
            lane = self._lanes[name]
            if not lane:
                self._deficit[name] = 0.0       # idle lanes hoard nothing
                self._advance(cls, len(names))
                continue
            if self._parked(name):
                self._advance(cls, len(names))
                continue
            cost = request_cost(lane[0])
            if self._deficit[name] >= cost:
                req = lane.popleft()
                self._deficit[name] -= cost
                self.admitted_tokens[name] += cost
                if not lane:
                    self._deficit[name] = 0.0
                    self._advance(cls, len(names))
                # else: cursor stays — the lane keeps the slot while
                # its deficit lasts (classic DRR serves a burst).
                return req
            self._deficit[name] += self._quantum * self.spec(name).weight
            self._advance(cls, len(names))
            if not servable():
                return None
        return None                               # backstop, unreachable

    def _advance(self, cls: str, n: int) -> None:
        self._cursor[cls] = (self._cursor[cls] + 1) % max(n, 1)

    # -- lifecycle sweeps (mirror RequestQueue semantics) ---------------

    def expire(self, step: Optional[int], now: float) -> List:
        """Remove and return every queued request past its deadline —
        the engine finalizes them ``timeout`` exactly as it does for
        intake-queue expiries."""
        out: List = []
        for name in self._order:
            lane = self._lanes[name]
            if not lane:
                continue
            keep = deque()
            for req in lane:
                if req.expired(step, now):
                    out.append(req)
                else:
                    keep.append(req)
            if len(keep) != len(lane):
                self._lanes[name] = keep
                if not keep:
                    self._deficit[name] = 0.0
        return out

    def cancel(self, uid: str):
        for name in self._order:
            lane = self._lanes[name]
            for req in lane:
                if req.uid == uid:
                    lane.remove(req)
                    if not lane:
                        self._deficit[name] = 0.0
                    return req
        return None

    def reject_overbudget_heads(self) -> List:
        """Pop every request that can provably never admit (head cost
        exceeds the tenant's remaining budget; budgets never
        replenish).  Called by the engine once intake is drained so
        parked work reaches a terminal status instead of wedging the
        run loop.  Stops at the first admissible head per lane —
        later steps will admit it normally."""
        out: List = []
        for name in self._order:
            lane = self._lanes[name]
            while lane and self._parked(name):
                out.append(lane.popleft())
            if not lane:
                self._deficit[name] = 0.0
        return out

    def drain(self) -> List:
        """Pop everything (interactive lanes first, spec order, FIFO
        within lane) — engine shutdown finalizes them ``drained``."""
        out: List = []
        for cls in _CLASSES:
            for name in self._class_names(cls):
                lane = self._lanes[name]
                while lane:
                    out.append(lane.popleft())
                self._deficit[name] = 0.0
        return out

    # -- introspection --------------------------------------------------

    def pending(self) -> int:
        return sum(len(lane) for lane in self._lanes.values())

    def admissible_pending(self) -> int:
        """Queued requests in lanes whose head could admit right now —
        budget-parked lanes excluded (strict per-tenant FIFO: a parked
        head blocks everything behind it).  The idle-vs-tick signal:
        a drive loop with only parked work must WAIT, not spin virtual
        time forward."""
        return sum(len(self._lanes[n]) for n in self._order
                   if self._lanes[n] and not self._parked(n))

    def pending_by_tenant(self) -> Dict[str, int]:
        return {n: len(self._lanes[n]) for n in self._order
                if self._lanes[n]}

    def summary(self) -> Dict[str, Dict[str, object]]:
        """Per-tenant scheduling ledger for summary records: only
        tenants that actually appeared (admitted or queued) — the
        default-tenant path stays byte-identical when unarmed because
        the engine never builds a scheduler at all."""
        out: Dict[str, Dict[str, object]] = {}
        for name in self._order:
            if not (self.admitted_tokens[name] or self._lanes[name]):
                continue
            spec = self.spec(name)
            rec: Dict[str, object] = {
                "weight": float(spec.weight),
                "slo_class": spec.slo_class,
                "admitted_tokens": int(self.admitted_tokens[name]),
                "queued": len(self._lanes[name]),
            }
            if spec.budget is not None:
                rec["budget"] = int(spec.budget)
            out[name] = rec
        return out
