"""sched/ — the multi-tenant scheduling stratum (ISSUE 19).

In-package convenience surface.  Like fleet/__init__.py and
spec/__init__.py this module is deliberately NOT on the graftlint
jax-free contract: importing it via the package walks the jax-carrying
apex_example_tpu/__init__.py edge.  Jax-free callers (fleet router,
tools) load sched/prefix.py and sched/tenants.py by FILE PATH.
"""

from .fair import DEFAULT_QUANTUM, FairScheduler, request_cost
from .prefix import chain_hashes, hash_prefix, overlap
from .tenants import (DEFAULT_SPEC, DEFAULT_TENANT, SLO_CLASSES,
                      TenantSpec, parse_tenants, tenant_names)

__all__ = [
    "DEFAULT_QUANTUM", "FairScheduler", "request_cost",
    "chain_hashes", "hash_prefix", "overlap",
    "DEFAULT_SPEC", "DEFAULT_TENANT", "SLO_CLASSES",
    "TenantSpec", "parse_tenants", "tenant_names",
]
