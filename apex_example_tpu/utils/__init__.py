from apex_example_tpu.utils.meters import AverageMeter, Throughput, accuracy

__all__ = ["AverageMeter", "Throughput", "accuracy"]
