"""Checkpoint/resume via orbax (reference: torch.save/load of model +
optimizer + amp.state_dict on rank 0; SURVEY.md §4.5, §6).

The saved pytree is (step, params, batch_stats, opt_state, scaler fields) —
crucially including the loss-scaler state, whose survival across resume the
reference tests explicitly (apex test_checkpointing.py).  orbax handles
sharded arrays natively, so the same call works single-chip and under a mesh;
process 0 coordinates the write in multi-host settings.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, Optional

import jax
import orbax.checkpoint as ocp

from apex_example_tpu.engine import TrainState

# Host-state sidecar files live NEXT to the orbax step dirs (not inside
# them — orbax owns the step dir's contents and garbage-collects it
# whole).  One JSON file per retained step.
_HOST_STATE_FMT = "host_state-{step}.json"
_HOST_STATE_GLOB = "host_state-*.json"


class CheckpointManager:
    """Thin manager: save(state), restore(template) -> state, latest step.

    Beyond the device pytree, a checkpoint can carry a **host-state
    sidecar** (``host_state-<step>.json``): the loop position (epoch /
    step-in-epoch / data index) and host PRNG state that live outside the
    TrainState.  The device state alone resumes *a* run; the sidecar is
    what makes resume *exact* — mid-epoch position preserved, the
    synthetic data stream continued rather than the epoch restarted
    (train.py's resume path consumes it; the resilience grace save
    writes it).
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                 create=True))

    def save(self, state: TrainState, step: Optional[int] = None,
             wait: bool = True,
             host_state: Optional[Dict[str, Any]] = None) -> None:
        """``wait=False`` returns as soon as the device arrays are snapshot
        and lets orbax's background thread do the serialization/IO — the
        async-checkpoint mode (train.py --async-checkpoint): training
        overlaps the write, at the cost of holding one extra copy of the
        state until it lands.  A later save (or close) joins the pending
        write first, so checkpoints never interleave.

        ``host_state`` (a JSON-serializable dict) is written synchronously
        as the step's sidecar — it is host data and tiny, so it never
        rides the async path (a sidecar must not outrun or trail the
        arrays it describes by more than the orbax commit window)."""
        step = int(state.step) if step is None else step
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if host_state is not None:
            self.save_host_state(step, host_state)
        if wait:
            self._mgr.wait_until_finished()

    # -------------------------------------------------- host-state sidecar

    def _host_state_path(self, step: int) -> str:
        return os.path.join(self.directory, _HOST_STATE_FMT.format(step=step))

    def save_host_state(self, step: int, host_state: Dict[str, Any]) -> None:
        """Atomic write (tmp + rename: a preemption mid-write must not
        leave a torn sidecar next to a good checkpoint), pruned to the
        manager's retention window."""
        path = self._host_state_path(int(step))
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(host_state, fh)
        os.replace(tmp, path)
        kept = sorted(self.host_state_steps())
        for old in kept[:-self.max_to_keep]:
            try:
                os.remove(self._host_state_path(old))
            except OSError:  # pragma: no cover
                pass

    def host_state_steps(self):
        steps = []
        for path in glob.glob(os.path.join(self.directory,
                                           _HOST_STATE_GLOB)):
            stem = os.path.basename(path)[len("host_state-"):-len(".json")]
            if stem.isdigit():
                steps.append(int(stem))
        return steps

    def load_host_state(self, step: Optional[int] = None
                        ) -> Optional[Dict[str, Any]]:
        """Sidecar for ``step`` (default: the latest checkpoint's), or
        None — pre-sidecar checkpoints stay restorable; the caller falls
        back to deriving position from ``state.step``."""
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        path = self._host_state_path(int(step))
        if not os.path.exists(path):
            return None
        try:
            with open(path) as fh:
                return json.load(fh)
        except (OSError, json.JSONDecodeError):  # pragma: no cover
            return None

    def wait_until_finished(self) -> None:
        """Join any pending async save."""
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, template: TrainState,
                step: Optional[int] = None) -> TrainState:
        """Restore into the structure of ``template`` (shapes/shardings from
        a freshly created state — restore before jit warmup, SURVEY.md §4.5).
        """
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        abstract = jax.tree_util.tree_map(
            ocp.utils.to_shape_dtype_struct, template)
        return self._mgr.restore(step,
                                 args=ocp.args.StandardRestore(abstract))

    def close(self):
        self._mgr.close()


def restore_params(directory: str, step: Optional[int] = None):
    """Template-free restore of just the ``params`` subtree — the serving
    path (serve.py).

    Training restore needs a TrainState template because orbax restores
    into the template's shapes/shardings, and the optimizer state's
    structure depends on which optimizer trained the run.  Serving wants
    none of that: restore the saved pytree raw (nested dicts, the
    StandardRestore no-template form) and keep only ``params`` — the one
    subtree whose structure the model itself defines.
    """
    mgr = ocp.CheckpointManager(os.path.abspath(directory))
    try:
        step = mgr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {directory}")
        restored = mgr.restore(step, args=ocp.args.StandardRestore())
        if not isinstance(restored, dict) or "params" not in restored:
            raise ValueError(
                f"checkpoint at {directory} step {step} holds no 'params' "
                "subtree (not a TrainState checkpoint?)")
        return restored["params"]
    finally:
        mgr.close()


def restore_under_mesh(mgr: CheckpointManager, state: TrainState, mesh,
                       zero_optimizer=None) -> TrainState:
    """Restore a checkpoint into a state that will run under ``mesh``.

    The trap (every mesh-resume path hits it): orbax restores INTO the
    template's shardings, and a fresh ``create_train_state`` template is
    committed to a single device — a sharded train step would then reject
    the restored state ("incompatible devices").  Re-place the template
    replicated over the mesh first (the DP/CP contract: state replicated,
    batch sharded), then restore.  With a ZeRO ``zero_optimizer``
    (DistributedFusedAdam), its optimizer state is placed per the
    optimizer's own ``state_spec()`` — sharded over the data axis — so the
    restored shards land where the ZeRO step expects them.

    Templates that are ALREADY mesh-placed (the TP/PP paths place theirs
    via gspmd/bert_pp state shardings) do not need this; restore into them
    directly.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P
    rep = NamedSharding(mesh, P())
    sh = jax.tree_util.tree_map(lambda _: rep, state)
    if zero_optimizer is not None:
        opt_sh = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), zero_optimizer.state_spec(),
            is_leaf=lambda v: isinstance(v, P))
        sh = sh.replace(opt_state=opt_sh)
    return mgr.restore(jax.device_put(state, sh))
