"""Metrics utilities (reference harness: AverageMeter / accuracy /
reduce_tensor; SURVEY.md §3.5)."""

from __future__ import annotations

import time
from typing import Tuple

import jax.numpy as jnp


class AverageMeter:
    """Running average — same surface as the reference harness's meter."""

    def __init__(self, name: str = "", fmt: str = ":f"):
        self.name, self.fmt = name, fmt
        self.reset()

    def reset(self):
        self.val = 0.0
        self.sum = 0.0
        self.count = 0
        self.avg = 0.0

    def update(self, val, n: int = 1):
        val = float(val)
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / max(self.count, 1)

    def __str__(self):
        return f"{self.name} {self.val:.4f} ({self.avg:.4f})"


def accuracy(logits: jnp.ndarray, labels: jnp.ndarray,
             topk: Tuple[int, ...] = (1,)) -> Tuple[jnp.ndarray, ...]:
    """Top-k accuracy in percent (matches the reference's accuracy())."""
    maxk = max(topk)
    top = jnp.argsort(-logits, axis=-1)[..., :maxk]
    correct = top == labels[..., None]
    return tuple(
        100.0 * jnp.mean(correct[..., :k].any(axis=-1).astype(jnp.float32))
        for k in topk)


class Throughput:
    """images/sec (or tokens/sec) meter with warmup skipping.

    ``warmup_steps=0`` starts the clock at construction and counts every
    step (the old form never set ``start`` — ``seen_steps`` begins at 1
    so it could never equal 0 — and ``rate`` stayed 0.0 forever).
    ``warmup_steps=K`` starts the clock at the end of step K and counts
    items from step K+1 on, excluding compile/warmup from the rate.
    """

    def __init__(self, warmup_steps: int = 2):
        self.warmup = max(int(warmup_steps), 0)
        self.items = 0
        self.seen_steps = 0
        self.start: float | None = \
            time.perf_counter() if self.warmup == 0 else None

    def step(self, n_items: int):
        self.seen_steps += 1
        if self.seen_steps == self.warmup:
            self.start = time.perf_counter()
            self.items = 0
        elif self.start is not None:
            self.items += n_items

    @property
    def rate(self) -> float:
        if self.start is None or self.items == 0:
            return 0.0
        elapsed = time.perf_counter() - self.start
        return self.items / max(elapsed, 1e-9)
