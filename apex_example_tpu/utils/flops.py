"""Analytic FLOPs models for the benchmark configs → MFU accounting.

VERDICT r4 item 3: ``bench.py`` must state what fraction of the chip's peak
each throughput number represents, not just raw img/s / tok/s.  The models
here are deterministic closed forms (no device, no tracing):

- **Transformers** (BERT/GPT/TXL): the standard training-compute model —
  ``6 · N_matmul`` FLOPs per token (2 per MAC × 3 for fwd+bwd, counting
  every matmul weight: QKVO, FFN, the vocab head, TXL's relative-position
  projection) **plus** the attention quadratic ``12 · L · S_attn · d`` per
  token (QKᵀ and AV are S·d MACs each per token per layer, ×2 FLOPs/MAC
  ×3 train), which the 6N form omits.  Embedding gathers are not matmuls
  and count 0.  (Kaplan et al.'s C ≈ 6ND convention, with the attention
  term made explicit since seq/d is not small for the long-context rows.)
- **ResNets**: per-conv enumeration — each conv is ``2·K²·Cin·Cout·Hout²``
  FLOPs per image forward, training ×3 (dgrad and wgrad are each conv-
  shaped).  BN/ReLU/pool FLOPs are noise against the convs and count 0.

MFU uses the v5e bf16 peak (197 TFLOP/s/chip) uniformly — also for the
fp32 c1 row, so every row is comparable against the same roofline (the
fp32 row's MFU is then conservative: fp32 MXU peak is lower).
"""

from __future__ import annotations

V5E_BF16_PEAK_FLOPS = 197e12      # per chip; Cloud TPU v5e spec sheet


def mfu_pct(items_per_sec: float, flops_per_item: float,
            peak_flops: float = V5E_BF16_PEAK_FLOPS) -> float:
    """Model-FLOPs utilization in percent."""
    return 100.0 * items_per_sec * flops_per_item / peak_flops


# --------------------------------------------------------------------------
# ResNet
# --------------------------------------------------------------------------

_RESNET_CFG = {
    # stage_sizes, bottleneck
    "resnet18": ([2, 2, 2, 2], False),
    "resnet34": ([3, 4, 6, 3], False),
    "resnet50": ([3, 4, 6, 3], True),
    "resnet101": ([3, 4, 23, 3], True),
    "resnet152": ([3, 8, 36, 3], True),
}


def _resnet_convs(stage_sizes, bottleneck, image_size):
    """[(k, cin, cout, hout)] for the torchvision-parity geometry
    (models/resnet.py: 7×7/2 stem + 3×3/2 maxpool, stages at strides
    1,2,2,2, projection shortcut on each stage's first block)."""
    convs = []
    h = image_size // 2                      # stem stride 2
    convs.append((7, 3, 64, h))
    h = -(-h // 2)                           # maxpool stride 2 (SAME)
    cin = 64
    for si, n_blocks in enumerate(stage_sizes):
        f = 64 * 2 ** si
        for b in range(n_blocks):
            s = 2 if (si > 0 and b == 0) else 1
            hout = -(-h // s)
            if bottleneck:
                convs += [(1, cin, f, h), (3, f, f, hout),
                          (1, f, 4 * f, hout)]
                cout = 4 * f
            else:
                convs += [(3, cin, f, hout), (3, f, f, hout)]
                cout = f
            if b == 0 and (s != 1 or cin != cout):
                convs.append((1, cin, cout, hout))
            cin, h = cout, hout
    return convs


def resnet_train_flops_per_image(arch: str, image_size: int,
                                 num_classes: int) -> float:
    stage_sizes, bottleneck = _RESNET_CFG[arch]
    convs = _resnet_convs(stage_sizes, bottleneck, image_size)
    fwd = sum(2.0 * k * k * cin * cout * hout * hout
              for k, cin, cout, hout in convs)
    fwd += 2.0 * 512 * (4 if bottleneck else 1) * num_classes   # fc
    return 3.0 * fwd


# --------------------------------------------------------------------------
# Transformers
# --------------------------------------------------------------------------

def transformer_train_flops_per_token(*, num_layers: int, d_model: int,
                                      d_ff: int, vocab_size: int,
                                      attn_span: int,
                                      extra_proj_per_layer: int = 0) -> float:
    """``attn_span``: sequence length each query attends over (seq for
    BERT/GPT; seq + mem_len for Transformer-XL's recurrence).
    ``extra_proj_per_layer``: extra d→d matmul params per layer beyond
    QKVO+FFN (TXL's relative-position r_net: d·d)."""
    per_layer_params = 4 * d_model * d_model + 2 * d_model * d_ff \
        + extra_proj_per_layer
    n_matmul = num_layers * per_layer_params + d_model * vocab_size
    attn = 12.0 * num_layers * attn_span * d_model
    return 6.0 * n_matmul + attn


def model_train_flops_per_token(model, seq_len: int) -> float:
    """Dispatch on the framework's model families by their config attrs."""
    if hasattr(model, "d_inner"):            # TransformerXL
        return transformer_train_flops_per_token(
            num_layers=model.num_layers, d_model=model.d_model,
            d_ff=model.d_inner, vocab_size=model.vocab_size,
            attn_span=seq_len + model.mem_len,
            extra_proj_per_layer=model.d_model * model.d_model)
    # BERT / GPT (MoE: each token still runs one expert FFN per layer under
    # top-1; top-2 doubles the FFN term — model FLOPs follow routed compute)
    ff_mult = getattr(model, "moe_top_k", 1) if getattr(
        model, "moe_experts", 0) else 1
    return transformer_train_flops_per_token(
        num_layers=model.num_layers, d_model=model.hidden_size,
        d_ff=model.intermediate_size * ff_mult, vocab_size=model.vocab_size,
        attn_span=seq_len)
