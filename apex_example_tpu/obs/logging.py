"""Rank-aware logging — the replacement for train.py's old
``print = lambda *a, **k: None`` monkeypatch.

The reference harness's contract is "rank 0 prints, workers are silent";
the monkeypatch implemented the second half by deleting worker output
entirely.  ``rank_print`` keeps the first half byte-identical (rank 0
writes through the real ``print``, so existing log scrapers and the
capsys-based tests see unchanged bytes) and upgrades the second: non-zero
ranks route the same line to the ``apex_example_tpu`` python logger at
DEBUG, where ``logging.basicConfig(level=DEBUG)`` or a handler can
recover it when debugging a worker.
"""

from __future__ import annotations

import builtins
import io
import logging
import sys

LOGGER_NAME = "apex_example_tpu"


def get_logger(name: str = LOGGER_NAME) -> logging.Logger:
    """The package logger; lazily given a stderr handler so DEBUG lines
    from non-zero ranks are recoverable without configuring logging."""
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(name)s %(levelname)s %(message)s"))
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


def _process_index() -> int:
    # Resolved per call, not at import: rank is only known after
    # maybe_initialize_distributed(), which runs well after this module
    # is imported.  Single-process (and pre-init) resolves to 0.
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def rank_print(*args, sep: str = " ", end: str = "\n", file=None,
               flush: bool = False) -> None:
    """``print``-compatible emitter: rank 0 IS ``print`` (same bytes,
    same kwargs); other ranks log the rendered line at DEBUG."""
    rank = _process_index()
    if rank == 0:
        builtins.print(*args, sep=sep, end=end, file=file, flush=flush)
        return
    buf = io.StringIO()
    builtins.print(*args, sep=sep, end="", file=buf)
    get_logger().debug("rank %d: %s", rank, buf.getvalue())
