"""Profiler windows: capture a ``jax.profiler`` trace for exactly steps
N..M instead of the old whole-run ``--prof`` dump.

Whole-run traces of a long run are useless twice over: the file is huge,
and the interesting steps (steady state, or a specific regression window)
drown in compile and warmup.  A window names the steps:

    --profile-window 5:8      # trace steps 5 through 8, run-relative,
                              # 1-based, inclusive on both ends

Step indices are *run-relative* (the Nth step this process executes),
not global-step values — a resumed run's window is counted from the
resume point, which is what you want when profiling a restarted job.

Async dispatch caveat: the step call returns at enqueue, so stopping the
trace right after step M's dispatch would truncate its device work.
``on_step_end`` therefore blocks on the step's metrics (any output
pytree) before ``stop_trace`` when a blocker is supplied.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax

from apex_example_tpu.obs.logging import rank_print

DEFAULT_TRACE_DIR = "/tmp/apex_tpu_trace"


def parse_window(spec: str) -> Tuple[int, int]:
    """``"N:M"`` -> (N, M), 1-based inclusive; raises ValueError on
    malformed specs so argparse surfaces a clean message."""
    parts = spec.split(":")
    if len(parts) != 2:
        raise ValueError(f"--profile-window {spec!r}: expected N:M")
    try:
        start, stop = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(f"--profile-window {spec!r}: N and M must be "
                         "integers") from None
    if start < 1 or stop < start:
        raise ValueError(f"--profile-window {spec!r}: need 1 <= N <= M")
    return start, stop


class ProfilerWindow:
    """Start/stop a jax profiler trace around run-relative steps N..M.

    Call ``on_step_start(i)`` before dispatching step ``i`` (1-based) and
    ``on_step_end(i, blocker=metrics)`` after it.  ``close()`` is the
    safety net for runs shorter than M — an open trace is always stopped.
    """

    def __init__(self, spec: str, logdir: Optional[str] = None):
        self.start, self.stop = parse_window(spec)
        # Resolved at call time (not a def-time default) so tests and
        # embedders can repoint DEFAULT_TRACE_DIR.
        self.logdir = logdir or DEFAULT_TRACE_DIR
        self.active = False
        self.done = False

    def on_step_start(self, step_index: int) -> None:
        if self.done or self.active or step_index != self.start:
            return
        jax.profiler.start_trace(self.logdir)
        self.active = True

    def on_step_end(self, step_index: int, blocker=None) -> None:
        if not self.active or step_index < self.stop:
            return
        if blocker is not None:
            jax.block_until_ready(blocker)
        jax.profiler.stop_trace()
        self.active = False
        self.done = True
        rank_print(f"profile window [{self.start}:{self.stop}] written to "
                   f"{self.logdir}")

    def close(self, blocker=None) -> None:
        if self.active:
            if blocker is not None:
                jax.block_until_ready(blocker)
            jax.profiler.stop_trace()
            self.active = False
            self.done = True
            rank_print(f"profile window truncated (run ended before step "
                       f"{self.stop}) — partial trace in {self.logdir}")


def make_profiler_window(spec: Optional[str],
                         logdir: Optional[str] = None
                         ) -> Optional[ProfilerWindow]:
    """None-propagating ctor for flag plumbing."""
    return ProfilerWindow(spec, logdir) if spec else None
