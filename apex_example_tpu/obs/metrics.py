"""Metrics registry + sinks: counters, gauges, histograms, a rank-aware
JSONL sink, and the TensorBoard adapter.

The registry is host-side and deliberately dumb — plain python numbers,
no device sync.  Callers fetch device scalars (``float(...)``) before
updating it; the telemetry emitter (obs/telemetry.py) owns that cadence.

Sink contract (the JSONL schema obs/schema.py defines): one JSON object
per line, one file per run, flushed per record so a killed run keeps
every step it completed.  Rank-awareness mirrors the reference harness's
"rank 0 logs" rule: by default only the main process writes; with
``all_ranks=True`` every process writes its own per-host file
(``path.rank<K>`` for K > 0) — concurrent writers never share a file.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional

from apex_example_tpu.obs import slo as _slo
from apex_example_tpu.obs.schema import SCHEMA_VERSION  # noqa: F401


class Counter:
    """Monotonic count (steps, overflows, records emitted)."""

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> int:
        if n < 0:
            raise ValueError(f"counter {self.name}: inc({n}) < 0")
        self.value += n
        return self.value


class Gauge:
    """Last-value-wins scalar (loss scale, learning rate, memory)."""

    def __init__(self, name: str):
        self.name = name
        self.value: Optional[float] = None

    def set(self, value) -> float:
        self.value = float(value)
        return self.value


def nearest_rank(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over a PRE-SORTED sequence: the
    ceil(q/100 * n)-th value (1-based), clamped.  The one percentile
    convention in the repo — Histogram and the serving engine's summary
    use this function; tools/metrics_lint.py carries a standalone copy
    of the same formula because the thin clients must run without the
    package installed.  (The old truncating int(q/100 * n) biased HIGH
    on small even samples: p50 of [1, 2, 3, 4] returned 3, not 2.)"""
    if not sorted_vals:
        return 0.0
    idx = math.ceil(q / 100.0 * len(sorted_vals)) - 1
    return sorted_vals[min(max(idx, 0), len(sorted_vals) - 1)]


class Histogram:
    """Streaming distribution (step times, span durations): exact
    count/sum/min/max plus a bounded sample for percentiles."""

    def __init__(self, name: str, max_samples: int = 4096):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._max_samples = max_samples
        self._samples: List[float] = []

    def observe(self, value) -> None:
        value = float(value)
        self.count += 1
        self.sum += value
        self.min = min(self.min, value)
        self.max = max(self.max, value)
        if len(self._samples) < self._max_samples:
            self._samples.append(value)
        else:
            # Bounded trailing window (ring buffer): percentiles reflect
            # the most recent max_samples observations — i.e. steady
            # state, not compile/warmup.  count/sum/min/max stay exact
            # over the full run.
            self._samples[self.count % self._max_samples] = value

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def percentile(self, q: float) -> float:
        return nearest_rank(sorted(self._samples), q)

    def summary(self) -> Dict[str, float]:
        if not self.count:
            return {"count": 0}
        # sum is exact over the full run (like count/min/max — not the
        # bounded sample): compile-time TOTALS ride it into the run
        # summary, separately from the per-step time distribution.
        return {"count": self.count, "mean": self.mean, "sum": self.sum,
                "min": self.min, "max": self.max, "p50": self.percentile(50),
                "p95": self.percentile(95)}

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold another histogram's observations into this one (ISSUE
        16): count/sum/min/max stay exact; the bounded sample pools
        both trails, stride-subsampled deterministically when the pool
        exceeds max_samples.  While the pooled trail fits the bound the
        merged percentiles EQUAL those of one histogram fed both
        streams — the ground truth fleet_report re-pools raw trails
        for."""
        if other.count == 0:
            return self
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        pooled = self._samples + other._samples
        if len(pooled) > self._max_samples:
            step = len(pooled) / self._max_samples
            pooled = [pooled[int(i * step)]
                      for i in range(self._max_samples)]
        self._samples = pooled
        return self


class LogBucketHistogram:
    """Mergeable streaming quantile sketch (DDSketch-style fixed log
    boundaries, ISSUE 16) — the cross-replica counterpart of the exact
    ``Histogram``: percentiles carry a declared RELATIVE-error bound
    ``alpha`` instead of a bounded trailing sample, and two sketches
    merge by bucket-count addition, so replica sketches aggregate into
    a fleet percentile no re-pooled raw trail is needed for.

    Thin class face over the dict-sketch helpers in ``obs/slo.py`` (the
    canonical math — stdlib-only so the jax-free router and tools load
    it by file path); ``to_dict()``/``from_dict()`` expose the same
    JSON-native serialized form replica heartbeats carry."""

    def __init__(self, name: str, alpha: float = _slo.DEFAULT_ALPHA):
        self.name = name
        self._sk = _slo.sketch_new(alpha)

    @property
    def alpha(self) -> float:
        return self._sk["alpha"]

    @property
    def count(self) -> int:
        return self._sk["count"]

    def observe(self, value) -> None:
        _slo.sketch_add(self._sk, value)

    def merge(self, other) -> "LogBucketHistogram":
        """Fold another sketch in (a LogBucketHistogram or a serialized
        dict); alphas must match."""
        sk = other._sk if isinstance(other, LogBucketHistogram) else other
        self._sk = _slo.sketch_merge(self._sk, sk)
        return self

    def percentile(self, q: float) -> float:
        return _slo.sketch_percentile(self._sk, q)

    def summary(self) -> Dict[str, float]:
        return _slo.sketch_summary(self._sk)

    def to_dict(self) -> Dict[str, Any]:
        return {"alpha": self._sk["alpha"], "count": self._sk["count"],
                "zero": self._sk["zero"],
                "buckets": dict(self._sk["buckets"]),
                "min": self._sk["min"], "max": self._sk["max"]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any],
                  name: str = "") -> "LogBucketHistogram":
        h = cls(name, alpha=d["alpha"])
        h._sk = {"alpha": d["alpha"], "count": d["count"],
                 "zero": d["zero"], "buckets": dict(d["buckets"]),
                 "min": d["min"], "max": d["max"]}
        return h


class MetricsRegistry:
    """Named metric instruments, get-or-create, one namespace.

    Re-registering a name with a different instrument type is an error —
    a silent re-type would corrupt every consumer of the snapshot.
    """

    def __init__(self):
        self._metrics: Dict[str, Any] = {}

    def _get(self, name: str, cls):
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = cls(name)
        elif not isinstance(m, cls):
            raise ValueError(f"metric {name!r} already registered as "
                             f"{type(m).__name__}, not {cls.__name__}")
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def snapshot(self) -> Dict[str, Any]:
        """Plain-python dump: counters/gauges to their value, histograms
        to their summary dict — JSON-ready."""
        out: Dict[str, Any] = {}
        for name, m in sorted(self._metrics.items()):
            out[name] = m.summary() if isinstance(m, Histogram) else m.value
        return out


class JsonlSink:
    """Rank-aware JSONL writer (one file per run).

    ``rank``/``all_ranks`` default to the reference harness's logging
    rule: only the main process writes.  ``rank=None`` resolves the
    process index lazily at first write (after distributed init).
    """

    def __init__(self, path: str, all_ranks: bool = False,
                 rank: Optional[int] = None):
        self.path = path
        self.all_ranks = all_ranks
        self._rank = rank
        self._fh = None                         # guarded-by: _lock
        self._opened = False                    # guarded-by: _lock
        # Reentrant: the flight recorder's signal handler may interrupt the
        # main thread inside write() and write its crash_dump from the same
        # thread; the watchdog thread contends cross-thread.  Records stay
        # intact either way because each lands as ONE fh.write() call.
        self._lock = threading.RLock()
        self.records_written = 0                # guarded-by: _lock

    def _resolve_rank(self) -> int:
        if self._rank is None:
            from apex_example_tpu.obs.logging import _process_index
            self._rank = _process_index()
        return self._rank

    @property
    def active(self) -> bool:
        return self.all_ranks or self._resolve_rank() == 0

    def resolved_path(self) -> str:
        rank = self._resolve_rank()
        return self.path if rank == 0 else f"{self.path}.rank{rank}"

    def write(self, record: Dict[str, Any]) -> bool:
        """Write one record; returns False when this rank doesn't write.
        One file is one run (truncated at first open — validate_stream
        requires a single run_header; a write after close() re-opens in
        append mode instead of destroying the run); flushed per line, so
        a killed run keeps every record it emitted."""
        if not self.active:
            return False
        with self._lock:
            if self._fh is None:
                path = self.resolved_path()
                parent = os.path.dirname(path)
                if parent:
                    os.makedirs(parent, exist_ok=True)
                self._fh = open(path, "a" if self._opened else "w")
                self._opened = True
            # One fh.write() per record: a C-level call is atomic w.r.t.
            # same-thread signal handlers, so a crash_dump written from a
            # handler never lands inside a half-written step line.
            line = json.dumps(record, separators=(",", ":")) + "\n"
            self._fh.write(line)
            self._fh.flush()
            self.records_written += 1
        return True

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_jsonl(path: str) -> List[Dict[str, Any]]:
    """Parse a sink file back into records (the round-trip tests and the
    tools/ thin clients share this)."""
    records = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    return records


class TensorBoardAdapter:
    """Feeds the existing tensorboardX writer path from a metrics dict —
    train.py's add_scalar call sites collapse into one ``scalars()``.
    A ``None`` writer makes every method a no-op, so call sites don't
    need their own ``if writer is not None`` guards."""

    def __init__(self, writer=None):
        self.writer = writer

    def scalars(self, values: Dict[str, float], step: int) -> None:
        if self.writer is None:
            return
        for tag, value in values.items():
            self.writer.add_scalar(tag, value, step)

    def close(self) -> None:
        if self.writer is not None:
            self.writer.close()


def now() -> float:
    """Wall-clock for record timestamps (one place to stub in tests)."""
    return time.time()
