"""apex_example_tpu.obs — the unified observability subsystem.

Two strata (README "Observability" / "Diagnostics" document the schema):

The emission layer (the happy path):

- :mod:`~apex_example_tpu.obs.logging`   rank-aware logging
  (``rank_print``: rank 0 is byte-identical to ``print``; workers log at
  DEBUG instead of being silenced).
- :mod:`~apex_example_tpu.obs.metrics`   metrics registry (counters /
  gauges / histograms), the rank-aware JSONL sink, and the TensorBoard
  adapter feeding train.py's existing writer path.
- :mod:`~apex_example_tpu.obs.spans`     host-side ``perf_counter``
  spans mirroring the device-side ``jax.named_scope`` phase labels the
  engine emits, so host and xprof timelines share names.
- :mod:`~apex_example_tpu.obs.telemetry` the per-step telemetry emitter
  (loss, scale, grad norm, overflow count, step time, items/sec, compile
  delta, memory) and :mod:`~apex_example_tpu.obs.profiler` windows
  (``--profile-window N:M``).

The diagnostics stratum (the failure path, schema v2):

- :mod:`~apex_example_tpu.obs.flight`    flight recorder — last-K step
  ring + crash hooks (signals/excepthook/atexit/faulthandler) emitting
  ``crash_dump`` + an aborted run summary on abnormal exit.
- :mod:`~apex_example_tpu.obs.watchdog`  stall watchdog thread —
  ``stall`` records with all-thread stacks when no step completes within
  a deadline; optional one-shot profiler window.
- :mod:`~apex_example_tpu.obs.numerics`  overflow provenance — per-
  module non-finite counts fused into the engine's finite-check pass,
  surfaced as ``overflow_event`` records naming the offending module(s).

The cost stratum (what XLA compiled, schema v6):

- :mod:`~apex_example_tpu.obs.costmodel` compiled-graph cost
  observability — jitted step functions re-routed through the AOT path
  so every compilation yields a ``compile_event`` (wall time, lowering
  hash, recompile ordinal) and a ``cost_model`` record (harvested
  flops/bytes/memory + roofline verdict).  ``--cost-model`` on
  train.py / bench.py / serve.py; ``tools/cost_report.py`` is the
  jax-free report.

The trace stratum (per-request/per-tick timelines, schema v9):

- :mod:`~apex_example_tpu.obs.trace`  the trace-event emitter (pure
  stdlib): ``--trace`` on serve.py / train.py arms a process-default
  :class:`Tracer`; host spans, the serve engine's tick/request
  lifecycle and the supervisor's restart decisions then land as
  ``trace_event`` records on the metrics stream, exported to
  Chrome/Perfetto by ``tools/trace_export.py``.

The streaming-SLO stratum (windowed online percentiles, schema v14):

- :mod:`~apex_example_tpu.obs.slo`  mergeable log-bucket quantile
  sketches (DDSketch-style, bounded relative error), SLO spec parsing,
  error-budget burn-rate scoring, and the :class:`SloTracker` the
  serve engine folds per-request latencies into — ``--slo`` on
  serve.py / fleet.py arms it; ``tools/slo_report.py`` renders the
  window timeline.  Pure stdlib (jax-free by contract, like schema) so
  the router and the report tools can load it by file path.
  :class:`~apex_example_tpu.obs.metrics.LogBucketHistogram` is the
  registry-side face over the same sketch.

The JSONL schema itself lives in :mod:`~apex_example_tpu.obs.schema`
(pure stdlib — tools can validate without importing jax).
"""

from apex_example_tpu.obs import costmodel, trace
from apex_example_tpu.obs.costmodel import CostModel
from apex_example_tpu.obs.trace import Tracer
from apex_example_tpu.obs.flight import FlightRecorder, format_thread_stacks
from apex_example_tpu.obs.logging import get_logger, rank_print
from apex_example_tpu.obs.metrics import (Counter, Gauge, Histogram,
                                          JsonlSink, LogBucketHistogram,
                                          MetricsRegistry,
                                          TensorBoardAdapter, nearest_rank,
                                          read_jsonl)
from apex_example_tpu.obs.numerics import NumericsMonitor, module_grad_stats
from apex_example_tpu.obs.profiler import (DEFAULT_TRACE_DIR, ProfilerWindow,
                                           make_profiler_window,
                                           parse_window)
from apex_example_tpu.obs.schema import (SCHEMA_VERSION, validate_record,
                                         validate_stream)
from apex_example_tpu.obs.spans import (PHASES, current_span, device_span,
                                        set_default_registry, span)
from apex_example_tpu.obs.telemetry import TelemetryEmitter, \
    device_memory_stats
from apex_example_tpu.obs.watchdog import StallWatchdog

__all__ = [
    "CostModel", "Counter", "DEFAULT_TRACE_DIR", "FlightRecorder", "Gauge",
    "Histogram",
    "JsonlSink", "LogBucketHistogram", "MetricsRegistry",
    "NumericsMonitor", "PHASES",
    "ProfilerWindow", "SCHEMA_VERSION", "StallWatchdog", "TelemetryEmitter",
    "TensorBoardAdapter", "Tracer", "current_span", "device_memory_stats",
    "device_span", "format_thread_stacks", "get_logger",
    "make_profiler_window", "module_grad_stats", "nearest_rank",
    "parse_window", "rank_print", "read_jsonl", "set_default_registry",
    "span",
    "validate_record", "validate_stream",
]
