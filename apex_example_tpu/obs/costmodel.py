"""Compiled-graph cost observability: harvest what XLA already knows.

Every jitted step function the repo runs is compiled exactly once per
(function, abstract signature) — and at that moment XLA has computed the
program's FLOPs, HBM bytes accessed, transcendental count and buffer
sizes.  Today none of it reaches the telemetry stream; the only byte
accounting is a hand-enumerated arithmetic script
(tools/byte_accounting.py) and MFU comes from closed-form models
(utils/flops.py).  This module closes the loop: an instrumentation
layer that routes a jitted function through the AOT path
(``fn.lower(*args).compile()``), executes the resulting ``Compiled``
object from then on — the run compiles nothing it would not have
compiled anyway, the dispatch-cache compile simply moves here — and
turns each compilation into two schema-v6 records:

``compile_event``  one per compilation — wall time of lower and
                   compile, a lowering hash (the compile-cache
                   identity: same hash ⇒ same program ⇒ a recompile is
                   a cache miss, not new work), and the per-name
                   compile ordinal ``n_compiles`` the recompile-
                   regression guard counts.
``cost_model``     the harvested ``cost_analysis()`` (flops, bytes
                   accessed, transcendentals) and ``memory_analysis()``
                   (argument/output/temp/generated-code bytes) plus the
                   analytic roofline position: arithmetic intensity,
                   compute-vs-HBM time at the peak constants, the
                   binding-side verdict, and the MFU ceiling that
                   intensity admits.  Backends that omit an analysis
                   (CPU reports ``generated_code_size_in_bytes`` 0 and
                   some backends raise) degrade those fields to
                   ``null`` rather than dropping the record.

The roofline constants default to the v5e numbers the repo already
standardizes on: ``utils.flops.V5E_BF16_PEAK_FLOPS`` (197 TFLOP/s bf16)
and the bandwidth ``tools/bw_micro.py`` measured on the tunnel chip
(375 GB/s; spec is 819).  On the CPU rig the verdict is therefore "what
this program would be bound by on the TPU target" — the program costs
are backend-portable, the constants are the target's.

``tools/cost_report.py`` (jax-free) joins the ``cost_model`` records
against measured ``step_time_ms`` from the same stream: per-function
roofline tables, analytic-vs-measured gap, recompile tallies — the
decision-grade input the parallelism auto-planner (ROADMAP item 4)
needs.

Usage (what train.py/bench.py/serve.py do under ``--cost-model``)::

    cm = CostModel(sink=jsonl_sink, registry=registry, run_id=run_id)
    costmodel.set_default(cm)
    ...
    step_fn = costmodel.instrument("train_step", step_fn)   # no-op
    ...                                                     # without a
    costmodel.set_default(None)                             # default

``instrument`` is deliberately forgiving: a callable without the AOT
surface (``.lower``), or one whose lowering fails, falls back to direct
calls — instrumentation must never break a run it observes.
"""

from __future__ import annotations

import hashlib
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

from apex_example_tpu.obs.metrics import now
from apex_example_tpu.utils.flops import V5E_BF16_PEAK_FLOPS

# tools/bw_micro.py on the tunnel chip (PERF.md; byte_accounting.py's
# --measured-bw default).  Spec sheet HBM bw for v5e is 819 GB/s.
MEASURED_HBM_GBPS = 375.0

# Retention cap for the per-function StableHLO text kept for the
# recompile-cause diff: past this size graftlint's diff_lowerings
# refuses to diff anyway (its MAX_DIFF_CHARS), so holding multi-MB
# serve-step lowerings in a long-lived process would buy nothing.
_MAX_HLO_RETAIN_CHARS = 2_000_000

# CompiledMemoryStats attribute -> cost_model field.
_MEMORY_FIELDS = (
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("temp_size_in_bytes", "temp_bytes"),
    ("generated_code_size_in_bytes", "generated_code_bytes"),
)


def _leaf_sig(leaf):
    """Hashable abstract descriptor of one argument leaf.  Arrays key on
    (shape, dtype, weak_type) — weak_type included because the compiled
    executable rejects a weak/strong mismatch the way a jit dispatch
    would transparently recompile for.  Python scalars key on their bare
    type (jit traces them weakly-typed and value-independent, so the
    value must not split the key).  No string building: this runs on
    EVERY instrumented call, and host overhead here would land inside
    the measured step_time_ms the roofline report joins against."""
    aval = getattr(leaf, "aval", None)
    if aval is not None:
        return (aval.shape, aval.dtype, getattr(aval, "weak_type", False))
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), dtype, False)
    return type(leaf)


def signature(args: tuple, kwargs: dict) -> Tuple:
    """The abstract call signature a jit dispatch would key on (tree
    structure + per-leaf shape/dtype/weak-type, all hashable objects —
    no serialization).  Two calls with the same signature share one
    compiled executable; a new signature is a recompile."""
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (treedef, tuple(_leaf_sig(l) for l in leaves))


def _first_computation(analysis) -> Dict[str, float]:
    """cost_analysis() returns a list of per-computation dicts on some
    jax versions and a bare dict on others; the entry point's is
    first."""
    if isinstance(analysis, (list, tuple)):
        return dict(analysis[0]) if analysis else {}
    return dict(analysis) if analysis else {}


def text_hash(text: str) -> str:
    """The lowering-hash formula over already-extracted StableHLO text
    (one place, shared with the instrumented AOT path that also keeps
    the text for the recompile-cause diff)."""
    return "sha256:" + hashlib.sha256(text.encode()).hexdigest()[:32]


def lowering_hash(lowered) -> Optional[str]:
    """Stable identity of the lowered program (StableHLO text digest):
    two compilations with the same hash compiled the same program — the
    compile-cache identity recompile forensics key on."""
    try:
        text = lowered.as_text()
    except Exception:
        return None
    return text_hash(text)


def compile_counts(records) -> Dict[str, int]:
    """``compile_event`` records per instrumented function name, from an
    iterable of parsed JSONL records — the recompile-regression guard's
    helper (the tier-1 tests assert every count is exactly 1)."""
    counts: Dict[str, int] = {}
    for rec in records:
        if isinstance(rec, dict) and rec.get("record") == "compile_event":
            name = rec.get("name", "?")
            counts[name] = counts.get(name, 0) + 1
    return counts


class CostModel:
    """Builds instrumented wrappers and owns the roofline constants +
    record emission.  ``sink`` (an obs JsonlSink) receives the records;
    ``registry`` (a MetricsRegistry) additionally gets a
    ``compile_time_ms`` histogram and a ``compiles`` counter, which the
    telemetry emitter folds into the run summary as measured compile
    totals."""

    def __init__(self, sink=None, registry=None, run_id: Optional[str] = None,
                 peak_flops: float = V5E_BF16_PEAK_FLOPS,
                 hbm_gbps: float = MEASURED_HBM_GBPS):
        self.sink = sink
        self.registry = registry
        self.run_id = run_id
        self.peak_flops = float(peak_flops)
        self.hbm_gbps = float(hbm_gbps)
        self._counts: Dict[str, int] = {}
        self._wrapped: Dict[Tuple[str, int], "InstrumentedFn"] = {}
        self.events: List[Dict[str, Any]] = []
        # Last StableHLO text PER NAME (not per wrapper: re-instrumenting
        # a name with a fresh fn object shares the per-name compile
        # count, so it must share the diff baseline too — the second
        # compile of a name always gets its recompile_cause).  Texts
        # past the retention cap are dropped; the name is remembered so
        # oversized recompiles still get an honest (diff-less) cause.
        self._last_hlo: Dict[str, str] = {}
        self._hlo_dropped: Dict[str, bool] = {}

    # ------------------------------------------------------- wrapping

    def instrument(self, name: str, fn: Callable) -> Callable:
        """Wrap ``fn`` (idempotent per (name, fn): repeated calls — e.g.
        generate() re-fetching the same lru-cached decode loop — reuse
        one wrapper and with it one compiled executable)."""
        if isinstance(fn, InstrumentedFn):
            return fn
        key = (name, id(fn))
        wrapped = self._wrapped.get(key)
        if wrapped is None:
            wrapped = InstrumentedFn(self, name, fn)
            self._wrapped[key] = wrapped
        return wrapped

    @property
    def compile_counts(self) -> Dict[str, int]:
        return dict(self._counts)

    def recompile_cause(self, name: str,
                        text: Optional[str]) -> Optional[str]:
        """Diff ``name``'s new lowering text against the retained
        previous one (None on the first compile of a name), then roll
        the retention forward."""
        if text is None:
            return None
        prev = self._last_hlo.get(name)
        cause = None
        if prev is not None:
            cause = _recompile_cause(prev, text)
        elif self._hlo_dropped.get(name):
            cause = ("previous lowering exceeded the retention cap "
                     f"({_MAX_HLO_RETAIN_CHARS} chars) — no diff; "
                     "compare lowering_hash values instead")
        if len(text) > _MAX_HLO_RETAIN_CHARS:
            self._last_hlo.pop(name, None)
            self._hlo_dropped[name] = True
        else:
            self._last_hlo[name] = text
            self._hlo_dropped[name] = False
        return cause

    # ------------------------------------------------------- emission

    def _write(self, rec: Dict[str, Any]) -> None:
        self.events.append(rec)
        if self.sink is not None:
            self.sink.write(rec)

    def on_compile(self, name: str, *, compile_ms: float, lower_ms: float,
                   lhash: Optional[str],
                   recompile_cause: Optional[str] = None) -> None:
        self._counts[name] = self._counts.get(name, 0) + 1
        rec: Dict[str, Any] = {
            "record": "compile_event",
            "time": now(),
            "name": name,
            "compile_ms": round(compile_ms, 3),
            "lower_ms": round(lower_ms, 3),
            "n_compiles": self._counts[name],
            "platform": jax.default_backend(),
        }
        if lhash:
            rec["lowering_hash"] = lhash
        if recompile_cause:
            # schema v8: the recompile-regression gate's diagnosis — the
            # first structurally divergent op between this lowering and
            # the previous one for the same name (graftlint's HLO diff).
            rec["recompile_cause"] = recompile_cause
        if self.run_id:
            rec["run_id"] = self.run_id
        if self.registry is not None:
            self.registry.histogram("compile_time_ms").observe(compile_ms)
            self.registry.counter("compiles").inc()
        self._write(rec)

    def on_cost(self, name: str, compiled, lhash: Optional[str]) -> None:
        """Harvest + emit the ``cost_model`` record for one compiled
        executable; every analysis the backend omits degrades to
        ``null`` fields."""
        try:
            cost = _first_computation(compiled.cost_analysis())
        except Exception:
            cost = {}
        flops = cost.get("flops")
        bytes_accessed = cost.get("bytes accessed")
        rec: Dict[str, Any] = {
            "record": "cost_model",
            "time": now(),
            "name": name,
            "flops": flops,
            "bytes_accessed": bytes_accessed,
            "transcendentals": cost.get("transcendentals"),
            "peak_flops": self.peak_flops,
            "hbm_gbps": self.hbm_gbps,
        }
        mem = None
        try:
            mem = compiled.memory_analysis()
        except Exception:
            pass
        for attr, field in _MEMORY_FIELDS:
            value = getattr(mem, attr, None) if mem is not None else None
            rec[field] = int(value) if value is not None else None
        # flops may be an explicit 0.0 (a data-movement-only program):
        # the roofline is still well-defined (AI 0, hbm-bound).  Only
        # bytes_accessed == 0 makes the division meaningless.
        if flops is not None and bytes_accessed:
            ai = flops / bytes_accessed
            ridge = self.peak_flops / (self.hbm_gbps * 1e9)
            compute_ms = flops / self.peak_flops * 1e3
            hbm_ms = bytes_accessed / (self.hbm_gbps * 1e9) * 1e3
            rec["arithmetic_intensity"] = round(ai, 3)
            rec["ridge_flops_per_byte"] = round(ridge, 3)
            rec["compute_ms"] = round(compute_ms, 6)
            rec["hbm_ms"] = round(hbm_ms, 6)
            rec["analytic_min_ms"] = round(max(compute_ms, hbm_ms), 6)
            rec["roofline"] = ("compute-bound" if compute_ms >= hbm_ms
                               else "hbm-bound")
            # The MFU this intensity admits at the roofline — the
            # CEILING measured MFU can reach, not the achievement
            # (cost_report computes that from measured step times).
            rec["mfu_ceiling_pct"] = round(100.0 * min(1.0, ai / ridge), 2)
        if lhash:
            rec["lowering_hash"] = lhash
        if self.run_id:
            rec["run_id"] = self.run_id
        self._write(rec)


class InstrumentedFn:
    """A jitted callable re-routed through the AOT path.

    First call per abstract signature: ``lower`` + ``compile`` (timed,
    hashed, harvested), then the ``Compiled`` executes; later calls
    dispatch straight to it.  A signature never seen before is a
    recompile and emits a second ``compile_event`` for the same name —
    exactly the regression the guard exists to catch.  Anything that
    breaks the AOT path (no ``.lower``, lowering failure) degrades to
    direct calls: observation must never take down the run.
    """

    def __init__(self, cost_model: CostModel, name: str, fn: Callable):
        self._cm = cost_model
        self.name = name
        self._fn = fn
        self._compiled: Dict[Tuple, List[Any]] = {}
        self._sole = None        # fast path when exactly one sig exists
        self._degraded = False
        self._call_warned = False

    def __call__(self, *args, **kwargs):
        if self._degraded:
            return self._fn(*args, **kwargs)
        if self._sole is not None:
            # Steady-state fast path — the one-signature case the
            # recompile guard enforces: no per-call pytree flatten.
            # Host overhead here would land inside the measured
            # step_time_ms the roofline report joins against, so the
            # signature is only computed when the executable rejects
            # the args (exactly where a jit dispatch would go back to
            # its cache key too).
            try:
                return self._sole(*args, **kwargs)
            except TypeError:
                pass                         # not this signature
        key = signature(args, kwargs)
        for compiled in self._compiled.get(key, []):
            if compiled is self._sole:
                continue                     # already rejected above
            try:
                return compiled(*args, **kwargs)
            except TypeError:
                # An aval distinction the signature key missed (e.g. an
                # exotic sharding difference): the executable rejects
                # the args BEFORE running; try the key's other
                # executables before compiling another.
                continue
        # Unseen signature, or a key collision every cached executable
        # rejects — exactly where a jit dispatch would transparently
        # recompile, so compile (an honest compile_event) rather than
        # take down the run.
        compiled = self._aot_compile(args, kwargs)
        if compiled is None:                # degraded mid-flight
            return self._fn(*args, **kwargs)
        self._store(key, compiled)
        return compiled(*args, **kwargs)

    def _store(self, key, compiled) -> None:
        # APPEND under the key: two colliding-but-incompatible call
        # forms keep both executables, instead of evicting each other
        # into a compile ping-pong on alternating calls.
        self._compiled.setdefault(key, []).append(compiled)
        n = sum(len(v) for v in self._compiled.values())
        self._sole = compiled if n == 1 else None

    def __getattr__(self, attr):
        # Passthrough (lower/trace/etc.) so the wrapper stays a drop-in.
        # Private names raise instead of delegating — that also keeps a
        # half-constructed instance from recursing on self._fn.
        if attr.startswith("_"):
            raise AttributeError(attr)
        return getattr(self._fn, attr)

    def _aot_compile(self, args, kwargs):
        try:
            t0 = time.perf_counter()
            lowered = self._fn.lower(*args, **kwargs)
            t1 = time.perf_counter()
            compiled = lowered.compile()
            t2 = time.perf_counter()
        except Exception as e:
            # The run keeps going on direct calls, but an explicitly
            # requested --cost-model must not fail SILENTLY: say why
            # records are missing (package logger, not stdout —
            # default-verbosity output stays unchanged).
            from apex_example_tpu.obs.logging import get_logger
            if self._compiled:
                # The function AOT-compiles in general — THIS call's
                # args don't lower.  Degrade the call, not the
                # function: cached executables keep serving and later
                # signatures still compile + get recorded.
                if not self._call_warned:
                    self._call_warned = True
                    get_logger(__name__).warning(
                        "cost-model: one call form of %r failed to "
                        "AOT-compile (%s: %s); that form runs "
                        "uninstrumented — its dispatch-cache compile "
                        "is not recorded as a compile_event",
                        self.name, type(e).__name__, e)
                return None
            self._degraded = True
            get_logger(__name__).warning(
                "cost-model instrumentation disabled for %r "
                "(%s: %s); falling back to direct calls — no "
                "compile_event/cost_model records for it",
                self.name, type(e).__name__, e)
            return None
        text: Optional[str] = None
        try:
            text = lowered.as_text()
        except Exception:
            pass
        lhash = text_hash(text) if text is not None else None
        # Per-NAME diff baseline on the CostModel: the compile ordinal
        # is per name, so the diagnosis must be too.
        cause = self._cm.recompile_cause(self.name, text)
        self._cm.on_compile(self.name, compile_ms=(t2 - t1) * 1e3,
                            lower_ms=(t1 - t0) * 1e3, lhash=lhash,
                            recompile_cause=cause)
        self._cm.on_cost(self.name, compiled, lhash)
        return compiled


def _recompile_cause(prev_text: str, new_text: str) -> Optional[str]:
    """Name the first divergent op between two lowerings of one step
    (the graftlint HLO diff, jax-free text analysis).  Degrades to None
    when the linter package is not importable — the tally still lands,
    only the diagnosis is lost."""
    try:
        from tools.graftlint.hlo import diff_lowerings
    except Exception:
        return None
    try:
        diff = diff_lowerings(prev_text, new_text)
    except Exception:  # pragma: no cover — diagnosis must never crash
        return None
    if diff is None:
        return ("lowerings structurally identical — this recompile is "
                "a dispatch-cache miss, not a program change")
    return str(diff["summary"])


# ------------------------------------------------------ default instance

_default: Optional[CostModel] = None


def set_default(cost_model: Optional[CostModel]) -> None:
    """Install (or clear, with None) the process-default cost model the
    deep call sites — the serve engine's decode step, generate()'s
    decode loop — pick up without flag plumbing."""
    global _default
    _default = cost_model


def get_default() -> Optional[CostModel]:
    return _default


def instrument(name: str, fn: Callable) -> Callable:
    """Wrap ``fn`` under the default cost model; identity when none is
    installed (the un-flagged path stays zero-cost)."""
    if _default is None or fn is None:
        return fn
    return _default.instrument(name, fn)
