"""Overflow provenance: WHICH module produced the non-finite grads.

The amp engine's dynamic-scaling path already reads every grad element
once for the finite check (engine.py ``unscale_check`` phase); it reports
*that* grads overflowed but not *where*.  :func:`module_grad_stats` adds
per-top-level-module non-finite element counts and grad norms computed in
the SAME traced pass — XLA fuses the ``isfinite`` reductions into the
existing check, so provenance costs no extra HBM traffic (cf. *Operator
Fusion in XLA*, PAPERS.md) — and :class:`NumericsMonitor` turns those
stats into schema-valid ``overflow_event`` records host-side.

Modes (``--numerics-check``):

- ``off``       no stats in the step, no fetch, no records (default).
- ``overflow``  stats ride the step; fetched + recorded only on steps
                whose grads were non-finite (the cheap forensics mode —
                clean steps pay only the fused device reductions).
- ``always``    one record per step regardless (numerics regression
                hunting; every step pays the host fetch).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from apex_example_tpu.obs import metrics as metrics_lib

MODES = ("off", "overflow", "always")


def module_grad_stats(grads: Any) -> Dict[str, Dict[str, jnp.ndarray]]:
    """Traced per-top-level-module grad forensics.

    ``grads`` is a flax-style params dict; each top-level key (module
    name) maps to ``{"nonfinite": int32 count of non-finite elements,
    "grad_norm": f32 l2 norm}``.  Non-dict grads collapse to one
    ``"params"`` entry.  Call inside the jitted step, next to the finite
    check that already reads every element.
    """
    tree = grads if isinstance(grads, dict) and grads else {"params": grads}
    out: Dict[str, Dict[str, jnp.ndarray]] = {}
    for name, sub in tree.items():
        leaves = jax.tree_util.tree_leaves(sub)
        if not leaves:
            continue
        nonfinite = sum(
            jnp.sum(~jnp.isfinite(l)).astype(jnp.int32) for l in leaves)
        sq = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
        out[str(name)] = {"nonfinite": nonfinite,
                          "grad_norm": jnp.sqrt(sq)}
    return out


class NumericsMonitor:
    """Host side: fetch the step's ``numerics`` stats and emit
    ``overflow_event`` records naming the offending module(s).

    Wire-up shape (what train.make_telemetry does)::

        monitor = NumericsMonitor(sink, mode="overflow")
        emitter.add_observer(monitor.on_record)

    ``max_events`` bounds a pathological run (every step overflowing at
    --numerics-check always) to a finite record count.
    """

    def __init__(self, sink: metrics_lib.JsonlSink, mode: str = "overflow",
                 run_id: Optional[str] = None, max_events: int = 1000):
        if mode not in MODES:
            raise ValueError(f"numerics mode {mode!r}: expected one of "
                             f"{MODES}")
        self.sink = sink
        self.mode = mode
        self.run_id = run_id
        self.max_events = max_events
        self.events_emitted = 0

    def on_record(self, record, metrics) -> Optional[Dict[str, Any]]:
        """TelemetryEmitter observer form of :meth:`on_step`."""
        if record.get("record") != "step":
            return None
        return self.on_step(int(record.get("step", 0)), metrics)

    def on_step(self, step: int, metrics: Dict[str, Any]
                ) -> Optional[Dict[str, Any]]:
        """Inspect one step's raw metrics dict; returns the emitted
        record (or None when this step emits nothing)."""
        if self.mode == "off" or not isinstance(metrics, dict):
            return None
        stats = metrics.get("numerics")
        if stats is None:
            return None
        finite = True
        if "grads_finite" in metrics:
            finite = float(metrics["grads_finite"]) >= 1.0
        if self.mode == "overflow" and finite:
            return None
        if self.events_emitted >= self.max_events:
            return None
        fetched = {
            name: {"nonfinite": int(s["nonfinite"]),
                   "grad_norm": float(s["grad_norm"])}
            for name, s in stats.items()}
        modules: List[str] = sorted(
            name for name, s in fetched.items() if s["nonfinite"] > 0)
        rec: Dict[str, Any] = {
            "record": "overflow_event",
            "time": metrics_lib.now(),
            "step": int(step),
            "modules": modules,
            "module_stats": fetched,
            "mode": self.mode,
        }
        if self.run_id:
            rec["run_id"] = self.run_id
        for key in ("scale", "loss"):
            if key in metrics:
                try:
                    rec[key] = float(metrics[key])
                except (TypeError, ValueError):  # pragma: no cover
                    pass
        self.sink.write(rec)
        self.events_emitted += 1
        return rec
