"""Streaming SLO plane — pure stdlib, importable without jax.

The live counterpart of serve_report/fleet_report's offline percentiles
(ISSUE 16): a mergeable DDSketch-style log-bucket quantile sketch, the
``--slo`` spec parser, per-event good/bad scoring against an error
budget, and the tumbling-window tracker the serve engine folds requests
and gauges into.

Self-contained BY CONTRACT (the obs/schema.py pattern): this module
imports nothing but the stdlib, so the jax-free fleet router, fleet.py
and the thin report tools load it by FILE PATH
(``importlib.util.spec_from_file_location``) without executing the
jax-carrying package ``__init__`` chain.  graftlint's jax-free rule
names it in CONTRACT_FILES; keep it that way.

The sketch
----------
Fixed log-boundary buckets with relative-error bound ``alpha``: for
``gamma = (1 + alpha) / (1 - alpha)``, a value ``v > 0`` lands in bucket
``ceil(log_gamma(v))`` and is estimated back as
``2 * gamma**i / (gamma + 1)`` — within a factor ``(1 +- alpha)`` of
every value the bucket holds, so any percentile estimate is within
relative error ``alpha`` of the exact sample percentile.  Values
``<= 0`` share one zero bucket estimated as 0.0.  The serialized form is
a plain JSON object (bucket index -> count, string keys), so merging
across replicas is bucket-count addition — associative, commutative,
and possible on hosts that only have the JSONL.

Windows and burn rate
---------------------
A window scores each terminal request GOOD (status ok AND every spec'd
latency within target) or BAD (everything else the server owned);
``drained`` requeues belong to the next server and stay outside the
denominator.  The error budget is ``1 - availability``; the burn rate
is ``bad_fraction / budget`` — burn 1.0 spends the budget exactly,
burn > 1.0 is a breach and emits an ``slo_breach`` record.
"""

from __future__ import annotations

import math
import time
from typing import Any, Callable, Dict, List, Optional

DEFAULT_ALPHA = 0.01

# The availability an --slo spec gets when it names none: three nines,
# i.e. a 0.001 error budget.
DEFAULT_AVAILABILITY = 0.999

# Terminal statuses outside the good/bad denominator: a drained request
# was handed back for requeueing — its fate belongs to whoever serves
# it next, and counting it against THIS server's budget would make
# every graceful drain look like an outage.
EXCLUDED_STATUSES = frozenset({"drained"})

_SLO_KEYS = ("ttft_ms", "tpot_ms", "availability")


# --------------------------------------------------------------- sketch

def _gamma(alpha: float) -> float:
    return (1.0 + alpha) / (1.0 - alpha)


def sketch_new(alpha: float = DEFAULT_ALPHA) -> Dict[str, Any]:
    """A fresh empty sketch (the JSON-native dict form)."""
    if not 0.0 < alpha < 1.0:
        raise ValueError(f"alpha must be in (0, 1), got {alpha}")
    return {"alpha": alpha, "count": 0, "zero": 0, "buckets": {},
            "min": None, "max": None}


def sketch_add(sk: Dict[str, Any], value, n: int = 1) -> Dict[str, Any]:
    """Fold ``n`` observations of ``value`` into ``sk`` (in place)."""
    v = float(value)
    sk["count"] += n
    sk["min"] = v if sk["min"] is None else min(sk["min"], v)
    sk["max"] = v if sk["max"] is None else max(sk["max"], v)
    if v <= 0.0:
        sk["zero"] += n
        return sk
    idx = math.ceil(math.log(v) / math.log(_gamma(sk["alpha"])))
    key = str(idx)
    sk["buckets"][key] = sk["buckets"].get(key, 0) + n
    return sk


def sketch_merge(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    """A new sketch holding a's and b's observations.  Associative and
    commutative (bucket-count addition); alphas must match — merging
    across error bounds would silently inherit the looser one."""
    if a["alpha"] != b["alpha"]:
        raise ValueError(f"cannot merge sketches with different alphas "
                         f"({a['alpha']} vs {b['alpha']})")
    mins = [m for m in (a["min"], b["min"]) if m is not None]
    maxs = [m for m in (a["max"], b["max"]) if m is not None]
    out = {"alpha": a["alpha"], "count": a["count"] + b["count"],
           "zero": a["zero"] + b["zero"], "buckets": dict(a["buckets"]),
           "min": min(mins) if mins else None,
           "max": max(maxs) if maxs else None}
    for key, n in b["buckets"].items():
        out["buckets"][key] = out["buckets"].get(key, 0) + n
    return out


def sketch_percentile(sk: Dict[str, Any], q: float) -> float:
    """Nearest-rank percentile estimate: the bucket holding the
    ``ceil(q/100 * n)``-th observation, estimated at its log-midpoint —
    within relative error ``alpha`` of the exact sample percentile.
    Empty sketch -> 0.0; ranks inside the zero bucket -> 0.0."""
    n = sk["count"]
    if n == 0:
        return 0.0
    rank = min(max(math.ceil(q / 100.0 * n), 1), n)
    if rank <= sk["zero"]:
        return 0.0
    seen = sk["zero"]
    g = _gamma(sk["alpha"])
    for idx in sorted(int(k) for k in sk["buckets"]):
        seen += sk["buckets"][str(idx)]
        if seen >= rank:
            return 2.0 * (g ** idx) / (g + 1.0)
    return sk["max"] if sk["max"] is not None else 0.0


def sketch_summary(sk: Dict[str, Any]) -> Dict[str, Any]:
    """The percentile dict windows/summaries embed (JSON-safe)."""
    return {"count": sk["count"],
            "p50": sketch_percentile(sk, 50),
            "p90": sketch_percentile(sk, 90),
            "p99": sketch_percentile(sk, 99),
            "min": sk["min"] if sk["min"] is not None else 0.0,
            "max": sk["max"] if sk["max"] is not None else 0.0}


# ------------------------------------------------------- spec + scoring

def parse_slo(spec: str) -> Dict[str, Any]:
    """Parse an ``--slo`` flag: ``ttft_ms=500,tpot_ms=50,
    availability=0.99``.  At least one latency target is required;
    ``availability`` defaults to 0.999 and must leave a nonzero error
    budget (< 1.0).  Raises ValueError with a usable message."""
    out: Dict[str, Any] = {"ttft_ms": None, "tpot_ms": None,
                           "availability": DEFAULT_AVAILABILITY}
    parts = [p.strip() for p in str(spec).split(",") if p.strip()]
    if not parts:
        raise ValueError("empty --slo spec (expected e.g. "
                         "ttft_ms=500,tpot_ms=50,availability=0.99)")
    seen = set()
    for part in parts:
        key, eq, val = part.partition("=")
        key = key.strip()
        if not eq or key not in _SLO_KEYS:
            raise ValueError(f"bad --slo entry {part!r} (expected "
                             f"key=value with key in {_SLO_KEYS})")
        if key in seen:
            raise ValueError(f"duplicate --slo key {key!r}")
        seen.add(key)
        try:
            x = float(val)
        except ValueError:
            raise ValueError(f"--slo {key} is not a number: {val!r}")
        if key == "availability":
            if not 0.0 < x < 1.0:
                raise ValueError(f"--slo availability must be in (0, 1) "
                                 f"— 1.0 leaves a zero error budget, "
                                 f"got {val}")
        elif x <= 0.0:
            raise ValueError(f"--slo {key} must be > 0, got {val}")
        out[key] = x
    if out["ttft_ms"] is None and out["tpot_ms"] is None:
        raise ValueError("--slo needs at least one latency target "
                         "(ttft_ms= and/or tpot_ms=)")
    return out


def _normalize_spec(spec) -> Dict[str, Any]:
    if isinstance(spec, str):
        return parse_slo(spec)
    return {"ttft_ms": spec.get("ttft_ms"),
            "tpot_ms": spec.get("tpot_ms"),
            "availability": spec.get("availability",
                                     DEFAULT_AVAILABILITY)}


def score_event(spec: Dict[str, Any], status: str, *,
                ttft_ms=None, tpot_ms=None) -> Optional[bool]:
    """True = good, False = bad, None = outside the denominator.

    Good means the server delivered: status ok AND every latency the
    spec names is present and within target (an ok completion MISSING a
    spec'd latency counts bad — an unmeasured target is not a met one).
    """
    if status in EXCLUDED_STATUSES:
        return None
    if status != "ok":
        return False
    if spec.get("ttft_ms") is not None and (
            ttft_ms is None or ttft_ms > spec["ttft_ms"]):
        return False
    if spec.get("tpot_ms") is not None and (
            tpot_ms is None or tpot_ms > spec["tpot_ms"]):
        return False
    return True


def burn_rate(good: int, bad: int, availability: float) -> float:
    """bad_fraction / error_budget over one window.  burn 1.0 spends
    the window's budget exactly; > 1.0 is a breach.  An empty window
    burns nothing."""
    total = good + bad
    if total == 0:
        return 0.0
    return (bad / total) / (1.0 - availability)


def score_windows(scored: List[Optional[bool]], window_size: int,
                  availability: float) -> List[Dict[str, Any]]:
    """Tumbling event-count windows over a scored event sequence (True/
    False/None per terminal event, arrival order) — the PURE function
    the fleet router's summary verdict is computed from, so two calls
    over the same events agree bit-for-bit.  The trailing partial
    window is included."""
    out: List[Dict[str, Any]] = []
    for i in range(0, len(scored), window_size):
        chunk = scored[i:i + window_size]
        good = sum(1 for s in chunk if s is True)
        bad = sum(1 for s in chunk if s is False)
        out.append({"window": len(out), "requests": len(chunk),
                    "good": good, "bad": bad,
                    "burn_rate": burn_rate(good, bad, availability)})
    return out


def worst_window(windows: List[Dict[str, Any]]):
    """(index, burn) of the max-burn window, first on ties; (None, 0.0)
    when there are no windows."""
    idx, worst = None, 0.0
    for w in windows:
        if idx is None or w["burn_rate"] > worst:
            idx, worst = w["window"], w["burn_rate"]
    return idx, worst


# ------------------------------------------------------------- tracker

class SloTracker:
    """The serve engine's windowed SLO fold — pure host-side state.

    Terminal requests and per-tick gauges accumulate into the current
    tumbling window; at each boundary the window closes into one
    ``slo_window`` record (plus an ``slo_breach`` when its burn rate
    exceeds 1.0), emitted through the ``emit`` callback (a JsonlSink
    .write, or None to keep records off).  Windows close every
    ``window_ticks`` engine ticks when set (the deterministic mode
    tests pin), else every ``window_s`` wall seconds.  Windows with no
    terminal events are skipped, not emitted — an idle engine writes
    nothing.

    Cumulative (never-reset) latency sketches back ``summary()`` (the
    serve_summary ``slo`` dict) and ``sketch_state()`` (the compact
    serialized form replica heartbeats carry for the fleet rollup).
    Latency sketches fold status-ok completions only — the same
    population ``request_complete`` records cover, so the ci_gate
    sketch-vs-exact check compares like with like.
    """

    def __init__(self, spec, *, alpha: float = DEFAULT_ALPHA,
                 window_s: float = 1.0, window_ticks: int = 0,
                 emit: Optional[Callable[[Dict[str, Any]], Any]] = None,
                 run_id: Optional[str] = None, clock=None):
        self.spec = _normalize_spec(spec)
        self.alpha = alpha
        self.window_s = float(window_s)
        self.window_ticks = int(window_ticks or 0)
        self.emit = emit
        self.run_id = run_id
        self._clock = clock or time.time
        self.budget = 1.0 - self.spec["availability"]
        # cumulative
        self.good = 0
        self.bad = 0
        self.windows = 0
        self.breaches = 0
        self.worst_burn = 0.0
        self.worst_window: Optional[int] = None
        self.ttft = sketch_new(alpha)
        self.tpot = sketch_new(alpha)
        self.queue_wait = sketch_new(alpha)
        # current window
        self._reset_window()
        self._window_started = self._clock()

    def _reset_window(self) -> None:
        self._w_counts: Dict[str, int] = {}
        self._w_good = 0
        self._w_bad = 0
        self._w_ttft = sketch_new(self.alpha)
        self._w_tpot = sketch_new(self.alpha)
        self._w_queue = sketch_new(self.alpha)
        self._w_ticks = 0
        self._w_occ_sum = 0.0
        self._w_blocks_live: Optional[int] = None
        self._w_kv_bytes_live: Optional[int] = None

    def observe_request(self, status: str, *, ttft_ms=None, tpot_ms=None,
                        queue_wait_ms=None) -> None:
        """Fold one terminal request into the current window."""
        self._w_counts[status] = self._w_counts.get(status, 0) + 1
        verdict = score_event(self.spec, status, ttft_ms=ttft_ms,
                              tpot_ms=tpot_ms)
        if verdict is True:
            self.good += 1
            self._w_good += 1
        elif verdict is False:
            self.bad += 1
            self._w_bad += 1
        if status == "ok":
            if ttft_ms is not None:
                sketch_add(self.ttft, ttft_ms)
                sketch_add(self._w_ttft, ttft_ms)
            if tpot_ms is not None:
                sketch_add(self.tpot, tpot_ms)
                sketch_add(self._w_tpot, tpot_ms)
            if queue_wait_ms is not None:
                sketch_add(self.queue_wait, queue_wait_ms)
                sketch_add(self._w_queue, queue_wait_ms)
        if self.window_ticks <= 0:
            self._maybe_roll()

    def observe_tick(self, *, live_slots=None, num_slots=None,
                     blocks_live=None, kv_bytes_live=None) -> None:
        """Fold one engine tick's gauges; closes the window at a tick
        boundary (tick mode) or past the wall deadline (wall mode)."""
        self._w_ticks += 1
        if live_slots is not None and num_slots:
            self._w_occ_sum += live_slots / num_slots
        if blocks_live is not None:
            self._w_blocks_live = int(blocks_live)
        if kv_bytes_live is not None:
            self._w_kv_bytes_live = int(kv_bytes_live)
        if self.window_ticks > 0:
            if self._w_ticks >= self.window_ticks:
                self._close_window()
        else:
            self._maybe_roll()

    def flush(self) -> None:
        """Close the trailing partial window (idempotent) — call before
        reading ``summary()`` for a closing record."""
        self._close_window()

    def _maybe_roll(self) -> None:
        if self._clock() - self._window_started >= self.window_s:
            self._close_window()

    def _close_window(self) -> None:
        n = sum(self._w_counts.values())
        if n == 0:
            # Nothing terminal this window: restart the clock, carry no
            # record — gauges without requests score nothing.
            self._reset_window()
            self._window_started = self._clock()
            return
        burn = burn_rate(self._w_good, self._w_bad,
                         self.spec["availability"])
        idx = self.windows
        self.windows += 1
        if self.worst_window is None or burn > self.worst_burn:
            self.worst_burn, self.worst_window = burn, idx
        rec = {"record": "slo_window", "time": self._clock(),
               "window": idx, "requests": n, "good": self._w_good,
               "bad": self._w_bad, "burn_rate": burn,
               "counts": dict(self._w_counts)}
        if self._w_ttft["count"]:
            rec["ttft_ms"] = sketch_summary(self._w_ttft)
        if self._w_tpot["count"]:
            rec["tpot_ms"] = sketch_summary(self._w_tpot)
        if self._w_queue["count"]:
            rec["queue_wait_ms"] = sketch_summary(self._w_queue)
        if self._w_ticks:
            rec["ticks"] = self._w_ticks
            rec["occupancy"] = self._w_occ_sum / self._w_ticks
        if self._w_blocks_live is not None:
            rec["blocks_live"] = self._w_blocks_live
        if self._w_kv_bytes_live is not None:
            rec["kv_bytes_live"] = self._w_kv_bytes_live
        if self.run_id is not None:
            rec["run_id"] = self.run_id
        if self.emit is not None:
            self.emit(rec)
        if burn > 1.0:
            self.breaches += 1
            brec = {"record": "slo_breach", "time": self._clock(),
                    "window": idx, "burn_rate": burn, "requests": n,
                    "good": self._w_good, "bad": self._w_bad,
                    "budget": self.budget}
            if self.run_id is not None:
                brec["run_id"] = self.run_id
            if self.emit is not None:
                self.emit(brec)
        self._reset_window()
        self._window_started = self._clock()

    def summary(self) -> Dict[str, Any]:
        """The serve_summary ``slo`` dict (call ``flush()`` first so
        the trailing partial window is scored)."""
        return {"spec": dict(self.spec), "alpha": self.alpha,
                "good": self.good, "bad": self.bad,
                "windows": self.windows, "breaches": self.breaches,
                "worst_burn": self.worst_burn,
                "worst_window": self.worst_window,
                "verdict": "fail" if self.breaches else "pass",
                "ttft_ms": sketch_summary(self.ttft),
                "tpot_ms": sketch_summary(self.tpot),
                "queue_wait_ms": sketch_summary(self.queue_wait)}

    def sketch_state(self) -> Dict[str, Any]:
        """The compact serialized cumulative sketches a replica
        heartbeat carries (``replica_state.slo_sketch``) — JSON-safe,
        mergeable by any host holding this file."""
        return {"ttft_ms": {"alpha": self.ttft["alpha"],
                            "count": self.ttft["count"],
                            "zero": self.ttft["zero"],
                            "buckets": dict(self.ttft["buckets"]),
                            "min": self.ttft["min"],
                            "max": self.ttft["max"]},
                "tpot_ms": {"alpha": self.tpot["alpha"],
                            "count": self.tpot["count"],
                            "zero": self.tpot["zero"],
                            "buckets": dict(self.tpot["buckets"]),
                            "min": self.tpot["min"],
                            "max": self.tpot["max"]}}
