"""Flight recorder: make the failure path as observable as the happy one.

The telemetry emitter (obs/telemetry.py) only observes runs that end
well — a crashed run never reaches ``close()``, so its JSONL stream just
stops.  The flight recorder keeps a bounded ring of the last K step
records plus a config/environment snapshot, installs the process-level
failure hooks (``faulthandler``, SIGTERM/SIGINT handlers,
``sys.excepthook``, ``atexit``), and on abnormal exit writes two records
to the SAME JSONL sink the run was already streaming to:

- a ``crash_dump`` — reason, traceback or all-thread stacks, the last-K
  step ring, the metrics-registry snapshot, device memory, config + env;
- the run's ``run_summary`` marked ``aborted: true`` (via
  ``TelemetryEmitter.abort``), so consumers never have to infer an abort
  from a missing summary.

A clean ``close()`` disarms everything: handlers restored, atexit
unregistered, no records written.  Dump-once semantics: whichever hook
fires first (signal, unwinding exception seen by train.py's ``finally``,
excepthook backstop, atexit backstop) wins; the rest are no-ops.

Signal semantics: the dump is written, then the PREVIOUS disposition
runs — SIGTERM re-delivers with the prior handler restored (the process
still dies with exit status 143), SIGINT chains to Python's default
handler (KeyboardInterrupt unwinds normally, so ``finally`` blocks run).
"""

from __future__ import annotations

import atexit
import collections
import faulthandler
import os
import platform
import signal
import sys
import threading
import traceback
from typing import Any, Dict, Optional

from apex_example_tpu.obs import metrics as metrics_lib
from apex_example_tpu.obs.telemetry import (TelemetryEmitter,
                                            device_memory_stats)

# Bounded dump payloads: a crash record must stay one JSONL line that
# tools can parse, not a core file.
_MAX_TRACEBACK_CHARS = 16_000
_MAX_STACKS_CHARS = 16_000
DEFAULT_KEEP = 64


def format_thread_stacks(limit: int = _MAX_STACKS_CHARS) -> str:
    """One string with every live thread's current stack — the python-side
    analog of faulthandler's dump, but capturable into a JSON record.
    (Shared with obs/watchdog.py's stall records.)"""
    names = {t.ident: t.name for t in threading.enumerate()}
    parts = []
    for ident, frame in sorted(sys._current_frames().items()):
        name = names.get(ident, "?")
        parts.append(f"--- thread {name} ({ident}) ---\n"
                     + "".join(traceback.format_stack(frame)))
    out = "\n".join(parts)
    return out[-limit:] if len(out) > limit else out


def _json_safe_config(config: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    return {k: v for k, v in (config or {}).items()
            if isinstance(v, (str, int, float, bool, type(None)))}


class FlightRecorder:
    """Crash forensics bound to a run's JSONL sink.

    ``emitter`` is the run's TelemetryEmitter when there is one (train.py)
    — the recorder then rides its sink, snapshots its registry, and writes
    the aborted summary through ``emitter.abort``.  Sink-only callers
    (bench.py / accuracy.py) pass ``sink`` instead and get a crash_dump
    plus a minimal aborted summary.

    Wire-up shape (what train.make_telemetry does)::

        recorder = FlightRecorder(emitter, config=vars(args))
        recorder.install()
        emitter.add_observer(recorder.on_record)   # feeds the ring
        ...
        recorder.close()                           # clean exit: disarm
    """

    def __init__(self, emitter: Optional[TelemetryEmitter] = None,
                 sink: Optional[metrics_lib.JsonlSink] = None,
                 keep: int = DEFAULT_KEEP,
                 config: Optional[Dict[str, Any]] = None):
        if sink is None:
            if emitter is None:
                raise ValueError("FlightRecorder needs an emitter or a sink")
            sink = emitter.sink
        self.emitter = emitter
        self.sink = sink
        self.ring: collections.deque = collections.deque(maxlen=max(keep, 1))
        self.config = _json_safe_config(config)
        self._prev_signal: Dict[int, Any] = {}
        self._prev_excepthook = None
        self._installed = False
        self._closed = False
        self._dumped = False

    # ------------------------------------------------------------- feed

    def on_record(self, record: Dict[str, Any], metrics=None) -> None:
        """TelemetryEmitter observer: keep the last K step records."""
        if record.get("record") == "step":
            self.ring.append(record)

    # ------------------------------------------------------------ hooks

    def install(self, signals=(signal.SIGTERM, signal.SIGINT),
                excepthook: bool = True, at_exit: bool = True,
                enable_faulthandler: bool = True) -> None:
        """Arm the failure hooks.  Signal handlers only install from the
        main thread (CPython's constraint); embedders running the loop in
        a worker thread keep the excepthook/atexit coverage."""
        if self._installed:
            return
        self._installed = True
        if enable_faulthandler and not faulthandler.is_enabled():
            # Native faults (SIGSEGV/SIGABRT from a kernel or the runtime)
            # can't run python code — stderr stacks are the best possible.
            faulthandler.enable()
        if threading.current_thread() is threading.main_thread():
            for sig in signals:
                try:
                    self._prev_signal[sig] = signal.signal(sig,
                                                           self._on_signal)
                except (ValueError, OSError):  # pragma: no cover
                    pass
        if excepthook:
            self._prev_excepthook = sys.excepthook
            sys.excepthook = self._on_excepthook
        if at_exit:
            atexit.register(self._on_atexit)

    def release_signal(self, sig) -> None:
        """Hand ownership of ``sig`` back: restore the disposition that
        preceded ``install()`` and forget the signal, so a later
        ``close()`` cannot clobber whoever installs over it.  Used by the
        preemption grace path (resilience/preemption.py) to take over
        SIGTERM/SIGUSR1 — a preempted run must save and exit 75, not
        crash-dump and die 143; the recorder keeps the excepthook/atexit/
        faulthandler coverage for real crashes."""
        prev = self._prev_signal.pop(sig, None)
        if prev is None:
            return
        try:
            if signal.getsignal(sig) == self._on_signal:
                signal.signal(sig, prev)
        except (ValueError, OSError):  # pragma: no cover
            pass

    def close(self) -> None:
        """Clean-exit disarm: restore handlers, unregister atexit.  After
        this, no hook writes anything."""
        if self._closed:
            return
        self._closed = True
        for sig, prev in self._prev_signal.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):  # pragma: no cover
                pass
        self._prev_signal.clear()
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
        atexit.unregister(self._on_atexit)

    # ------------------------------------------------------------- dump

    def environment(self) -> Dict[str, str]:
        env = {"python": platform.python_version(),
               "platform": platform.platform(),
               "argv": " ".join(sys.argv)}
        try:
            import jax
            env["jax"] = jax.__version__
        except Exception:  # pragma: no cover
            pass
        return env

    def crash_dump(self, reason: str, exc_info=None,
                   thread_stacks: bool = False) -> Optional[Dict[str, Any]]:
        """Write the ``crash_dump`` record + the aborted run summary.
        Idempotent: the first caller wins (every hook funnels here)."""
        if self._dumped:
            return None
        self._dumped = True
        rec: Dict[str, Any] = {
            "record": "crash_dump",
            "time": metrics_lib.now(),
            "reason": reason,
            "env": self.environment(),
        }
        if self.config:
            rec["config"] = self.config
        if self.ring:
            rec["step"] = int(self.ring[-1].get("step", 0))
            rec["last_steps"] = list(self.ring)
        if self.emitter is not None:
            rec["run_id"] = self.emitter.run_id
            try:
                rec["registry"] = self.emitter.registry.snapshot()
            except Exception:  # pragma: no cover
                pass
        if exc_info is not None:
            tb = "".join(traceback.format_exception(*exc_info))
            rec["traceback"] = tb[-_MAX_TRACEBACK_CHARS:]
        if thread_stacks:
            rec["thread_stacks"] = format_thread_stacks()
        try:
            mem = device_memory_stats()
        except Exception:  # pragma: no cover
            mem = None
        if mem:
            rec["memory"] = mem
        self.sink.write(rec)
        if self.emitter is not None:
            self.emitter.abort(reason)
        else:
            self.sink.write({"record": "run_summary",
                             "time": metrics_lib.now(),
                             "steps": len(self.ring),
                             "overflow_count": 0,
                             "aborted": True, "abort_reason": reason})
            self.sink.close()
        return rec

    # ---------------------------------------------------- hook targets

    def _on_signal(self, signum, frame) -> None:
        name = signal.Signals(signum).name
        self.crash_dump(f"signal:{name}", thread_stacks=True)
        prev = self._prev_signal.get(signum, signal.SIG_DFL)
        try:
            signal.signal(signum, prev if not callable(prev)
                          else signal.SIG_DFL)
        except (ValueError, OSError):  # pragma: no cover
            pass
        if callable(prev):
            # SIGINT's default is signal.default_int_handler — chaining
            # raises KeyboardInterrupt here, so finally blocks still run.
            prev(signum, frame)
        else:
            # Re-deliver with the prior disposition restored: the process
            # exits with the conventional 128+signum status.
            os.kill(os.getpid(), signum)

    def _on_excepthook(self, etype, value, tb) -> None:
        # Backstop for exceptions that escape without passing a finally
        # that calls crash_dump (train.py's close_telemetry normally beats
        # this hook).  SystemExit is a normal CLI exit, not a crash.
        if not issubclass(etype, SystemExit):
            self.crash_dump(f"exception:{etype.__name__}",
                            exc_info=(etype, value, tb))
        prev = self._prev_excepthook or sys.__excepthook__
        prev(etype, value, tb)

    def _on_atexit(self) -> None:
        # Interpreter teardown without close(): os._exit-adjacent paths,
        # sys.exit deep in a library, a worker dropping the run on the
        # floor.  A clean close() unregisters this.
        self.crash_dump("atexit:run never closed")
