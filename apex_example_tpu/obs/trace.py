"""The trace-event stratum: per-tick / per-request / per-step timelines.

Pure stdlib ON PURPOSE (no jax import): the supervisor emits matching
``trace_event`` records from its jax-free side of the fence, and the
exporter (tools/trace_export.py) must run on hosts that only have the
JSONL files.

Histograms (obs/spans.py, obs/metrics.py) answer "how long does X take
on average"; a timeline answers "what did THIS request wait on".  A
:class:`Tracer` turns state transitions into schema-v9 ``trace_event``
records on the existing metrics stream, flag-gated (``--trace`` on
serve.py / train.py) so the default path emits nothing — byte-identical
streams with the flag off.

Record semantics (a deliberate subset of the Chrome trace-event
phases, so the export is a projection, not a translation):

``ph: "B"/"E"``  begin/end of a nested region on one ``tid`` row,
                 matched stack-wise per row (the engine's tick span);
``ph: "X"``      a complete span: ``ts`` + ``dur`` known at emission —
                 the shape used for everything reconstructed after the
                 fact (request lifecycle spans are emitted at terminal
                 time from the timestamps the request accumulated, so
                 a request stranded mid-flight can never leave an
                 unbalanced B behind);
``ph: "i"``      an instant (first_token, admit, drain markers).

Span identity: ``span_id`` / ``parent_id`` are stream-local strings;
``trace_id`` groups STREAMS — the supervisor passes it to children via
``APEX_TRACE_ID`` so a SIGTERM -> drain -> restart renders as ONE
timeline across attempt streams (tools/trace_export.py puts each
stream on its own process row).

Dual clocks: every ``ts``/``dur`` is ``time.perf_counter()`` (seconds)
— monotonic, the single basis for all duration math — and each stream
carries exactly one ``clock_sync`` record pairing a ``perf_counter``
reading with ``time.time()`` taken back-to-back, the anchor the
exporter uses to place streams (and an xprof device trace) on one
wall-clock axis.  Wall-clock appears in emitted records only; it is
never subtracted from a monotonic reading.
"""

from __future__ import annotations

import itertools
import os
import time
import uuid
from typing import Any, Dict, Optional

TRACE_ID_ENV = "APEX_TRACE_ID"

_PHASES = ("B", "E", "X", "i")


class Tracer:
    """Emits ``trace_event`` records to a sink (anything with
    ``write(dict)`` — the obs JsonlSink, or the supervisor's _Stream).

    ``trace_id`` defaults to the ``APEX_TRACE_ID`` environment variable
    (set by a supervising parent) and falls back to a fresh uuid — a
    standalone run is its own one-stream trace.  The ``clock_sync``
    anchor is written lazily with the first event, so arming a tracer
    on a run that never traces anything leaves the stream untouched.
    """

    def __init__(self, sink, trace_id: Optional[str] = None,
                 run_id: Optional[str] = None):
        self.sink = sink
        self.trace_id = (trace_id or os.environ.get(TRACE_ID_ENV)
                         or uuid.uuid4().hex[:12])
        self.run_id = run_id
        self.events = 0
        self._ids = itertools.count(1)
        self._synced = False

    # ------------------------------------------------------------ core

    def next_id(self) -> str:
        """A fresh stream-local span id."""
        return f"s{next(self._ids)}"

    def _clock_sync(self) -> None:
        """The per-stream clock anchor: one wall-clock reading paired
        with one monotonic reading, taken back-to-back.  Everything
        else in the stream is monotonic; the exporter maps via
        ``wall = time + (ts - this.ts)``."""
        rec: Dict[str, Any] = {
            "record": "clock_sync",
            "time": time.time(),
            "ts": time.perf_counter(),
            "trace_id": self.trace_id,
        }
        if self.run_id:
            rec["run_id"] = self.run_id
        self.sink.write(rec)
        self._synced = True

    def event(self, ph: str, name: str, *, ts: Optional[float] = None,
              dur: Optional[float] = None, tid: str = "main",
              cat: Optional[str] = None, span_id: Optional[str] = None,
              parent_id: Optional[str] = None,
              args: Optional[Dict[str, Any]] = None) -> None:
        """Emit one trace_event.  ``ts``/``dur`` are perf_counter
        seconds (``ts`` defaults to now)."""
        if ph not in _PHASES:
            raise ValueError(f"ph must be one of {_PHASES}, got {ph!r}")
        if not self._synced:
            self._clock_sync()
        rec: Dict[str, Any] = {
            "record": "trace_event",
            "ph": ph,
            "name": name,
            "ts": time.perf_counter() if ts is None else ts,
            "tid": tid,
            "trace_id": self.trace_id,
        }
        if dur is not None:
            rec["dur"] = dur
        if cat is not None:
            rec["cat"] = cat
        if span_id is not None:
            rec["span_id"] = span_id
        if parent_id is not None:
            rec["parent_id"] = parent_id
        if args:
            rec["args"] = args
        if self.run_id:
            rec["run_id"] = self.run_id
        self.sink.write(rec)
        self.events += 1

    # ------------------------------------------------------- sugar

    def begin(self, name: str, *, ts: Optional[float] = None,
              tid: str = "main", cat=None,
              span_id: Optional[str] = None, parent_id=None,
              args=None) -> str:
        """Open a nested region on ``tid``; returns its span id (pass
        it to children as ``parent_id``).  Must be closed by ``end`` on
        the same tid — stack-wise, like the Chrome B/E contract."""
        sid = span_id or self.next_id()
        self.event("B", name, ts=ts, tid=tid, cat=cat, span_id=sid,
                   parent_id=parent_id, args=args)
        return sid

    def end(self, name: str, *, ts: Optional[float] = None,
            tid: str = "main", args=None) -> None:
        self.event("E", name, ts=ts, tid=tid, args=args)

    def complete(self, name: str, ts: float, dur: float, *,
                 tid: str = "main", cat=None,
                 span_id: Optional[str] = None, parent_id=None,
                 args=None) -> str:
        """A complete span, timestamps known at emission (the
        reconstruct-after-the-fact shape)."""
        sid = span_id or self.next_id()
        self.event("X", name, ts=ts, dur=max(dur, 0.0), tid=tid, cat=cat,
                   span_id=sid, parent_id=parent_id, args=args)
        return sid

    def instant(self, name: str, *, ts: Optional[float] = None,
                tid: str = "main", cat=None, parent_id=None,
                args=None) -> None:
        self.event("i", name, ts=ts, tid=tid, cat=cat,
                   parent_id=parent_id, args=args)


# Process-default instance (the costmodel pattern): serve.py / train.py
# install one under --trace; the span layer and the serve engine consult
# it so call sites stay flag-free.
_default: Optional[Tracer] = None


def set_default(tracer: Optional[Tracer]) -> None:
    global _default
    _default = tracer


def get_default() -> Optional[Tracer]:
    return _default
