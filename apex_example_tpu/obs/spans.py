"""Host-side spans that mirror the device-side ``jax.named_scope`` phase
labels, so host timelines and xprof traces share one naming convention.

Two kinds of region exist in this stack and they need different tools:

- **Traced (device) regions** — code under ``jit``.  Host timing there
  is meaningless (it measures tracing, once); the right annotation is
  ``jax.named_scope``, which lands the label in the xprof timeline.
  :func:`device_span` is that, re-exported so the engine's phase names
  come from the single :data:`PHASES` table below.
- **Host regions** — the train loop's data fetch, step dispatch,
  checkpoint IO.  :func:`span` times those with ``perf_counter``, nests,
  and (optionally) feeds a ``span.<name>`` histogram in a
  :class:`~apex_example_tpu.obs.metrics.MetricsRegistry`.

Using the same names on both sides ("fwd_bwd" as a host span around a
block that is "fwd_bwd" in the device trace) is the point: a future perf
PR reads one vocabulary across JSONL telemetry and xprof.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import List, Optional

import jax

from apex_example_tpu.obs import trace as trace_lib

# Canonical phase labels.  The device-side entries are emitted by
# engine.make_train_step via device_span; the host-side entries by the
# train loop.  Keep README's "Observability" section in sync.
PHASES = (
    "data",             # host: batch synthesis / prefetcher fetch
    "step",             # host: step dispatch (+ fetch when telemetry is on)
    "fwd_bwd",          # device: forward + scaled backward
    "grad_allreduce",   # device: DDP gradient reduction
    "unscale_check",    # device: unscale + finite check
    "optimizer",        # device: fused optimizer apply
)

device_span = jax.named_scope

_tls = threading.local()
_default_registry = None


def set_default_registry(registry) -> None:
    """Registry every subsequent span records into (None disables)."""
    global _default_registry
    _default_registry = registry


class Span:
    """One timed host region; ``dur_ms`` is set when the context exits."""

    __slots__ = ("name", "t0", "dur_ms", "children", "span_id")

    def __init__(self, name: str):
        self.name = name
        self.t0 = time.perf_counter()
        self.dur_ms: Optional[float] = None
        self.children: List["Span"] = []
        # Allocated up front when a tracer is armed (--trace): children
        # exit FIRST, so the parent's id must exist before its own X
        # event is emitted.
        self.span_id: Optional[str] = None

    @property
    def dur_s(self) -> float:
        return (self.dur_ms or 0.0) / 1e3

    def path(self) -> str:
        return self.name


def _stack() -> List[Span]:
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


def current_span() -> Optional[Span]:
    stack = _stack()
    return stack[-1] if stack else None


@contextmanager
def span(name: str, registry=None, device: bool = False):
    """Time a host region.

    Nested spans attach to their parent (``Span.children``); completed
    spans feed ``span.<dotted.path>`` histograms in ``registry`` (or the
    default registry).  ``device=True`` additionally enters
    ``jax.named_scope(name)``, for host regions that also dispatch traced
    work — the xprof timeline then carries the same label.

    Yields the :class:`Span`; read ``sp.dur_ms`` after the ``with`` for
    the measured duration.

    With a default :class:`~apex_example_tpu.obs.trace.Tracer` armed
    (``--trace``), each completed span additionally lands as a
    schema-v9 ``trace_event`` (ph "X", tid = the host thread's name,
    parented on the enclosing span) — the histograms above are
    unchanged; the timeline is strictly additive.
    """
    stack = _stack()
    sp = Span(name)
    parent = stack[-1] if stack else None
    if parent is not None:
        parent.children.append(sp)
    tracer = trace_lib.get_default()
    if tracer is not None:
        sp.span_id = tracer.next_id()
    stack.append(sp)
    scope = jax.named_scope(name) if device else None
    if scope is not None:
        scope.__enter__()
    try:
        yield sp
    finally:
        if scope is not None:
            scope.__exit__(None, None, None)
        sp.dur_ms = (time.perf_counter() - sp.t0) * 1e3
        stack.pop()
        reg = registry if registry is not None else _default_registry
        if reg is not None:
            path = ".".join([s.name for s in stack] + [name])
            reg.histogram(f"span.{path}").observe(sp.dur_ms)
        if tracer is not None:
            tracer.complete(
                name, sp.t0, sp.dur_ms / 1e3, cat="span",
                tid=threading.current_thread().name,
                span_id=sp.span_id,
                parent_id=parent.span_id if parent is not None else None)
