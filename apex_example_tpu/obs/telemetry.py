"""Per-step telemetry: the emitter that turns a train loop's metrics dict
into schema-valid JSONL records (obs/schema.py).

The emitter owns the one deliberate cost of telemetry: fetching device
scalars each step is a host sync, so the whole layer is flag-gated
(``--metrics-jsonl``) and the default path never pays it.  Because the
fetch blocks until the step's metrics are materialized, the wall time
measured *after* the fetch includes device execution — that is what
``step_time_ms`` means.

First-step compile time is detected, not measured: the first step's wall
time is trace+compile+execute while steady-state steps are execute-only,
so ``run_summary.compile_est_ms = first_step_ms - median(rest)``.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Optional

import jax

from apex_example_tpu.obs import metrics as metrics_lib
from apex_example_tpu.obs.schema import SCHEMA_VERSION

# Memory-stats keys worth shipping (device.memory_stats() returns a much
# larger dict on TPU; these are the capacity-planning ones).
_MEMORY_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                "largest_alloc_size")


def device_memory_stats() -> Optional[Dict[str, int]]:
    """Subset of the first local device's memory_stats(), or None where
    the backend doesn't report (CPU)."""
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    out = {k: int(stats[k]) for k in _MEMORY_KEYS if k in stats}
    return out or None


def _scalar_metrics(metrics: Dict[str, Any]) -> Dict[str, float]:
    """Fetch every scalar in a step's metrics dict to python floats (this
    is the blocking device sync telemetry pays for)."""
    out = {}
    for key, value in metrics.items():
        try:
            out[key] = float(value)
        except (TypeError, ValueError):
            continue                      # non-scalar aux, skip
    return out


class TelemetryEmitter:
    """Emits run_header / step / run_summary records to a JsonlSink and
    (optionally) a MetricsRegistry + TensorBoardAdapter.

    Usage shape (what train.py does)::

        emitter = TelemetryEmitter(JsonlSink(path), registry=reg)
        emitter.run_header(config=vars(args), arch=args.arch)
        for ...:
            t0 = time.perf_counter()
            state, metrics = step_fn(state, batch)
            emitter.on_step(global_step=gs, epoch=e, metrics=metrics,
                            items=batch_items, t_start=t0)
        emitter.close()
    """

    def __init__(self, sink: metrics_lib.JsonlSink,
                 registry: Optional[metrics_lib.MetricsRegistry] = None,
                 memory_every: int = 10):
        self.sink = sink
        self.registry = registry or metrics_lib.MetricsRegistry()
        self.memory_every = memory_every
        self.run_id = uuid.uuid4().hex[:12]
        self._step_times_ms: List[float] = []
        self._overflows = 0
        self._steps = 0
        self._items = 0
        self._t_run0 = time.perf_counter()
        self._closed = False
        # Diagnostics hookup (obs/flight.py, obs/watchdog.py,
        # obs/numerics.py): each callback sees (step_record, raw_metrics)
        # after the record lands in the sink.
        self._observers: List = []

    def add_observer(self, callback) -> None:
        """``callback(record, metrics)`` runs after every emitted step —
        the flight recorder's ring, the watchdog's heartbeat, and the
        numerics monitor all subscribe here so the train loops stay a
        single ``emitter.on_step`` call."""
        self._observers.append(callback)

    def run_header(self, config: Dict[str, Any], argv: Optional[list] = None,
                   **extra) -> Dict[str, Any]:
        rec: Dict[str, Any] = {
            "record": "run_header",
            "schema": SCHEMA_VERSION,
            "time": metrics_lib.now(),
            "run_id": self.run_id,
            "num_devices": jax.device_count(),
            "num_processes": jax.process_count(),
            "process_index": jax.process_index(),
            "platform": jax.default_backend(),
            "config": {k: v for k, v in config.items()
                       if isinstance(v, (str, int, float, bool, type(None)))},
        }
        if argv is not None:
            rec["argv"] = [str(a) for a in argv]
        rec.update(extra)
        self.sink.write(rec)
        return rec

    def on_step(self, *, global_step: int, epoch: int,
                metrics: Dict[str, Any], items: int,
                t_start: float) -> Dict[str, Any]:
        """Fetch, record, emit one step.  ``t_start`` is the
        ``perf_counter`` taken immediately before the step dispatch; the
        elapsed time is measured after the metric fetch so it covers
        device execution."""
        values = _scalar_metrics(metrics)
        elapsed_ms = (time.perf_counter() - t_start) * 1e3
        self._steps += 1
        self._items += items
        self._step_times_ms.append(elapsed_ms)
        if values.get("grads_finite", 1.0) < 1.0:
            self._overflows += 1

        rec: Dict[str, Any] = {
            "record": "step",
            "time": metrics_lib.now(),
            "step": int(global_step),
            "epoch": int(epoch),
            "step_time_ms": round(elapsed_ms, 3),
            "items_per_sec": round(items / max(elapsed_ms / 1e3, 1e-9), 1),
            "overflow_count": self._overflows,
            # schema-required even when a step builder omits them — the
            # contract fields consumers key on.
            "loss": values.get("loss", 0.0),
            "scale": values.get("scale", 1.0),
        }
        for key in ("grad_norm", "grads_finite", "top1", "ppl",
                    "masked_acc", "lr"):
            if key in values:
                rec[key] = values[key]
        if self.memory_every and (self._steps - 1) % self.memory_every == 0:
            mem = device_memory_stats()
            if mem:
                rec["memory"] = mem

        reg = self.registry
        reg.counter("steps").inc()
        reg.counter("items").inc(items)
        reg.histogram("step_time_ms").observe(elapsed_ms)
        reg.gauge("loss").set(rec["loss"])
        reg.gauge("scale").set(rec["scale"])
        if "grad_norm" in rec:
            reg.gauge("grad_norm").set(rec["grad_norm"])

        self.sink.write(rec)
        for callback in self._observers:
            callback(rec, metrics)
        return rec

    def summary(self) -> Dict[str, Any]:
        rec: Dict[str, Any] = {
            "record": "run_summary",
            "time": metrics_lib.now(),
            "steps": self._steps,
            "overflow_count": self._overflows,
        }
        if self._step_times_ms:
            first = self._step_times_ms[0]
            rec["first_step_ms"] = round(first, 3)
            rest = sorted(self._step_times_ms[1:])
            if rest:
                steady = rest[len(rest) // 2]
                rec["steady_step_ms"] = round(steady, 3)
                # first step = trace + compile + execute; steady = execute.
                rec["compile_est_ms"] = round(max(first - steady, 0.0), 3)
            wall_s = time.perf_counter() - self._t_run0
            rec["items_per_sec"] = round(self._items / max(wall_s, 1e-9), 1)
        snap = self.registry.snapshot()
        span_hists = {
            name: summ
            for name, summ in snap.items()
            if name.startswith("span.") and isinstance(summ, dict)}
        if span_hists:
            rec["spans"] = span_hists
        # Measured compile totals (obs/costmodel.py feeds the histogram
        # under --cost-model): the first-vs-steady compile_est_ms above
        # stays as a cross-check, but consumers should prefer these.
        compile_hist = snap.get("compile_time_ms")
        if isinstance(compile_hist, dict) and compile_hist.get("count"):
            rec["compile_events"] = int(compile_hist["count"])
            rec["compile_ms_total"] = round(compile_hist["sum"], 3)
        return rec

    def preemption(self, signal_name: str, *, step: int,
                   checkpoint_step: Optional[int] = None,
                   saved: bool = False) -> Dict[str, Any]:
        """The graceful-preemption record (schema v4; the resilience
        grace path, resilience/preemption.py): written BEFORE the normal
        close, so the stream reads header, steps..., preemption,
        run_summary — and the summary stays un-aborted (a preempted run
        is resumable, not broken)."""
        rec: Dict[str, Any] = {
            "record": "preemption",
            "time": metrics_lib.now(),
            "run_id": self.run_id,
            "signal": str(signal_name),
            "step": int(step),
            "saved": bool(saved),
        }
        if checkpoint_step is not None:
            rec["checkpoint_step"] = int(checkpoint_step)
        self.sink.write(rec)
        return rec

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._steps:
            self.sink.write(self.summary())
        self.sink.close()

    def abort(self, reason: str) -> None:
        """The crash-path close (obs/flight.py): always write the run
        summary — even at 0 steps — marked ``aborted: true``, so stream
        consumers can tell a killed run from one that ended well."""
        if self._closed:
            return
        self._closed = True
        rec = self.summary()
        rec["aborted"] = True
        rec["abort_reason"] = reason
        self.sink.write(rec)
        self.sink.close()
