"""The telemetry JSONL schema — pure stdlib, importable without jax.

Every line a sink emits is one JSON object tagged by ``record``.

Version 1 (the happy path):

``run_header``   one per run, first line — identifies the run (id, argv,
                 config snapshot, device topology, platform).
``step``         one per train step — loss, loss scale, grad norm, step
                 wall time, items/sec, overflow accounting, memory.
``run_summary``  one per run, last line — first-step vs steady-state
                 step-time delta (the compile-time estimate), totals.
``bench``        one per bench.py measurement (the stdout JSON line's
                 sink twin).
``accuracy``     one per accuracy.py (seed, opt_level) cell.

Version 2 adds the diagnostics stratum (the failure path):

``crash_dump``      emitted by the flight recorder (obs/flight.py) on
                    abnormal exit — reason, traceback / thread stacks,
                    the last-K step records, registry snapshot, device
                    memory, config + environment.
``stall``           emitted by the stall watchdog (obs/watchdog.py) when
                    no step completes within the deadline — all-thread
                    stacks, seconds since the last step.
``overflow_event``  emitted by the numerics monitor (obs/numerics.py) —
                    names the top-level module(s) whose grads went
                    non-finite, with per-module counts and norms.

plus ``aborted``/``abort_reason`` on ``run_summary`` (a crashed run's
summary carries ``aborted: true``).  v2 is a strict superset of v1:
every v1 stream validates unchanged.

Version 3 adds the serving stratum (serve.py / serve/):

``request_complete``  one per finished inference request — token counts,
                      TTFT/TPOT, finish reason, slot/step provenance.
``serve_summary``     one per serving run, last line — request/token
                      totals, throughput, latency percentile dicts,
                      slot occupancy.

v3 is again a strict superset: every v1/v2 stream validates unchanged
(a serving stream carries a ``run_header`` but no ``run_summary`` —
``serve_summary`` is its closing record).

Version 4 adds the resilience stratum (resilience/; the recover path):

``preemption``  emitted by a ``--preempt-grace`` run that caught
                SIGTERM/SIGUSR1, saved a final checkpoint at the next
                step boundary and exited 75 (EX_TEMPFAIL) — the
                graceful counterpart of ``crash_dump`` (the run summary
                stays un-aborted).
``restart``     emitted by the auto-resume supervisor
                (tools/supervise.py) into its OWN stream when a child
                exits restartably — attempt index, exit code, reason
                (``preemption``/``crash``/``stall``), backoff, the
                child's last step.
``resume``      emitted by the supervisor when a launch attempt is
                rewritten to ``--resume`` an existing checkpoint.

plus ``restart_count``/``exit_code`` on ``run_summary`` (the
supervisor's closing record).  v4 is once more a strict superset: every
v1–v3 stream validates unchanged.

Version 5 adds the serving-resilience stratum (ISSUE 5: deadlines,
admission control, drain, serve-path faults):

``request_failed``  one per non-success request termination — status
                    ``timeout`` (deadline expired, queued or mid-
                    flight), ``cancelled``, or ``failed`` (slot-level
                    exception / degenerate-token guard, with the
                    traceback digest).
``shed``            one per request rejected by admission control
                    (``RequestQueue(max_pending=...)`` overflow).
``serve_drain``     emitted by a SIGTERM/SIGUSR1'd serve.py that
                    stopped admission, finished or deadline-evicted its
                    in-flight slots, handed queued requests back for
                    requeueing, and exited 75 (EX_TEMPFAIL) — the
                    serving counterpart of ``preemption``.

plus per-status counts (``completed``/``timed_out``/``shed``/
``cancelled``/``failed``/``drained``) and an ``availability`` ratio on
``serve_summary``.  v5 is once more a strict superset: every v1–v4
stream validates unchanged.

Version 6 adds the compiled-graph cost stratum (obs/costmodel.py;
``--cost-model`` on train.py / bench.py / serve.py):

``compile_event``  one per XLA compilation of an instrumented function
                   — lower/compile wall time, the lowering hash (the
                   compile-cache identity), and the per-name compile
                   ordinal ``n_compiles`` the recompile-regression
                   guard counts (a healthy run compiles each function
                   exactly once).
``cost_model``     the harvested ``cost_analysis()`` /
                   ``memory_analysis()`` for one compiled executable —
                   flops, HBM bytes accessed, transcendentals, buffer
                   sizes — plus the analytic roofline position
                   (arithmetic intensity, compute vs HBM time at the
                   peak constants, the binding-side verdict, MFU
                   ceiling).  Fields a backend omits are ``null``, not
                   absent (the CPU rig reports no generated-code size;
                   some backends omit whole analyses).

plus measured compile totals (``compile_ms_total``/``compile_events``)
on ``run_summary`` and the paged-KV waste baseline on
``serve_summary`` (``kv_bytes_reserved``/``kv_bytes_live``/
``slot_occupancy``/``kv_waste_pct``).  v6 is once more a strict
superset: every v1–v5 stream validates unchanged.
``tools/cost_report.py`` is the jax-free thin client that joins
``cost_model`` records against measured step times.

Version 7 adds the block-paged KV stratum (serve/slots.py; ISSUE 8) —
no new record types, new ``serve_summary`` fields:

``block_size``/``blocks_total``  the arena geometry (tokens per block,
                                 blocks per layer arena),
``blocks_live``                  per-tick histogram of arena blocks
                                 physically held by live slots,
``kv_bytes_committed``           per-tick histogram of admission-
                                 committed bytes (held + worst-case
                                 reserved blocks),
``prefix_hit_rate``              shared prompt tokens / total prompt
                                 tokens over every admission,
``cow_copies``                   copy-on-write block copies performed,
``rejected``                     requests terminated at admission as
                                 unservable (zero output budget) —
                                 ``request_failed`` gains the matching
                                 ``rejected`` status.

``kv_waste_pct`` becomes block-accurate (held-block bytes vs logically
live bytes; the dense layout's fixed full-page reservation measured
~92% on the smoke workload, the paged layout <= 40%).  v7 is once more
a strict superset: every v1–v6 stream validates unchanged.

Version 8 adds the static-analysis stratum's one record field
(tools/graftlint; ISSUE 9) — no new record types:

``recompile_cause``  on ``compile_event``, set from the second compile
                     of one name onward: the first structurally
                     divergent op between this lowering and the one it
                     replaced (graftlint's jax-free StableHLO diff), or
                     an explicit note that the programs are identical
                     (a dispatch-cache miss, not a graph change).  The
                     ``cost_report --fail-on-recompile`` gate prints it,
                     turning the recompile tally into a diagnosis.

v8 is once more a strict superset: every v1–v7 stream validates
unchanged.

Version 9 adds the trace-event stratum (obs/trace.py; ``--trace`` on
serve.py / train.py — README "Request tracing"):

``trace_event``  one timeline event: ``ph`` B/E (begin/end of a nested
                 region, matched stack-wise per ``tid`` row), X (a
                 complete span with ``dur``), or i (an instant);
                 ``ts``/``dur`` are MONOTONIC ``perf_counter`` seconds
                 — never wall-clock; ``span_id``/``parent_id`` build
                 the span tree, ``trace_id`` groups streams (a
                 supervised restart's attempt streams share one, via
                 the ``APEX_TRACE_ID`` env handoff).
``clock_sync``   exactly one per traced stream: a ``perf_counter``
                 reading (``ts``) paired with a back-to-back
                 ``time.time()`` (``time``) — the anchor
                 tools/trace_export.py uses to place streams (and an
                 xprof device trace) on one wall-clock axis.

Without ``--trace`` neither record is emitted — streams are
byte-identical to v8 runs.  v9 is once more a strict superset: every
v1–v8 stream validates unchanged.

Version 10 adds the fleet stratum (apex_example_tpu/fleet/; ``fleet.py``
— a jax-free router over N supervised serve replicas, README "Fleet
serving & chaos scenarios"):

``route``          one per router dispatch decision — which replica a
                   request was handed to, under which policy, on which
                   attempt, and why (``reason``: the initial dispatch,
                   a deadline-aware ``retry`` after a replica died, a
                   ``requeue_drain`` after a replica exited 75 and
                   handed its queued requests back, or a ``backlog``
                   drain once capacity returned).
``replica_state``  a replica health/lifecycle observation.  Emitted
                   from BOTH sides of the fence: a serve.py replica
                   (``--inbox`` mode) heartbeats its own
                   tick/pending/blocks_live/pid, and the router records
                   the transitions it acts on (healthy / stalled /
                   crashed / restarting / stopped), carrying the
                   supervisor's exit ``classification`` when one is
                   known.
``fleet_summary``  one per fleet run, last line of the router's stream
                   — request totals per terminal status, retry/requeue
                   accounting, ``lost`` (uids that never reached a
                   terminal status — the rolling-restart acceptance
                   pins this at 0), the fleet ``availability`` ratio
                   (ok / non-drained terminal across all replicas),
                   the per-replica breakdown and the routing-balance
                   stats, plus the scenario name + verdict when a
                   scripted chaos scenario drove the run.

plus ``classification`` on ``restart`` (the supervisor's verdict on how
the child died: ``preempted`` / ``crashed`` / ``stall_killed`` — so
fleet tooling distinguishes drains from crashes without re-parsing
child streams).  v10 is once more a strict superset: every v1–v9
stream validates unchanged.

Version 11 adds the quantization stratum (apex_example_tpu/quant/;
ISSUE 13 — ``--weight-quant``/``--kv-quant`` on serve.py,
``--quantized-allreduce`` on train.py):

``quant_event``  one per quantization application at startup — which
                 stratum quantized (``kind``: weights | kv), the
                 storage dtype, tensor/byte accounting and the scale
                 spread (the number the error bound is a multiple of).

plus precision fields on ``serve_summary``: ``kv_dtype`` /
``weight_dtype`` (the arena payload and weight storage dtypes — so
``kv_bytes_committed``/``kv_bytes_live`` are now interpretable as
DTYPE-ACCURATE bytes), and ``kv_bytes_per_token`` /
``kv_bytes_per_token_bf16`` (the actual vs bf16-equivalent per-token
cost; their ratio is the compression the serve_report QUANT line
renders and the ci_gate ``--quant-stream`` floor enforces).  v11 is
once more a strict superset: every v1–v10 stream validates unchanged.

Version 12 adds the sharded/disaggregated-serving stratum
(serve/disagg.py; ``--mesh dp,tp`` and ``--role prefill|decode`` on
serve.py):

``kv_handoff``  one per KV-cache handoff side: a prefill worker that
                chunk-prefilled a prompt into its paged arena and
                shipped the request's blocks (payload + int8 scales +
                fill level) emits ``direction: "out"``; the decode
                worker that scattered them into its own arena and
                took over decoding emits ``direction: "in"`` (with
                ``handoff_ms``, the out-stamp -> admission wall-clock
                transit, and ``requeued``, the times admission was
                deferred for free blocks).

plus sharding/role fields on ``serve_summary``: ``role`` (prefill /
decode / both), ``mesh`` / ``dp`` / ``tp`` (the registered serve mesh,
weights and KV arenas head-sharded over ``model``), and the handoff
accounting (``handoffs_out`` / ``handoffs_in`` / ``handoff_requeued``
/ ``handoff_bytes`` / ``handoff_ms`` percentiles); ``replica_state``
heartbeats gain ``kv_bytes_live`` (the dtype-accurate byte gauge the
fleet router's ``least_kv`` policy prefers over the raw block count).
v12 is once more a strict superset: every v1–v11 stream validates
unchanged.

Version 13 adds the crash-safe handoff stratum (ISSUE 15 —
serve/disagg.py's leased spool protocol and the disagg fleet
scenarios); no new record types:

``kv_handoff`` grows the lease/redelivery story: ``direction`` gains
the value "quarantine" (a corrupt/truncated payload parked at
``*.bad`` — the worker stays alive; ``spool_file``/``error`` name the
evidence), ``redelivered`` counts deliveries from a reclaimed or
adopted lease, and ``duplicate: true`` marks an idempotent re-admission
(the decode engine had already admitted the uid — the ack-crash window
— so nothing was scattered twice).  ``serve_summary`` gains
``handoff_duplicates`` / ``handoff_redelivered`` /
``handoff_quarantined``; ``replica_state`` heartbeats gain ``role``;
``fleet_summary`` gains the disagg topology + spool accounting
(``prefill_replicas`` / ``decode_replicas`` / ``handoffs`` /
``handoff_redelivered`` / ``in_spool``).  v13 is once more a strict
superset: every v1–v12 stream validates unchanged.

Version 14 adds the streaming SLO stratum (obs/slo.py; ``--slo`` on
serve.py / fleet.py — README "SLO monitoring"):

``slo_window``   one per closed tumbling window (every
                 ``--slo-window-s`` wall seconds or
                 ``--slo-window-ticks`` engine ticks on serve.py; every
                 ``--slo-window`` terminal events on the fleet router)
                 — good/bad event counts scored against the ``--slo``
                 spec, the window's error-budget ``burn_rate``
                 (bad fraction / (1 - availability)), per-status
                 counts, TTFT/TPOT/queue-wait percentile estimates
                 from the window's log-bucket sketch (relative-error
                 bound ``alpha``), and the latest
                 blocks_live/kv_bytes_live/occupancy gauge snapshot.
``slo_breach``   one per window whose burn rate exceeds 1.0 — the
                 window spent more than its whole error budget; names
                 the window and its burn/good/bad/budget numbers so an
                 alerting tail never needs the full stream.
``fleet_rollup``  the router's live cross-replica aggregation, one per
                 rollup interval: replica heartbeat sketches
                 (``replica_state.slo_sketch``) merged by bucket-count
                 addition into fleet-wide TTFT/TPOT percentiles, plus
                 the per-replica p50 breakdown, the max/median p50
                 ``skew`` and the worst replica's name (``straggler``)
                 — the live form of what fleet_report finds post-hoc.

plus ``slo_sketch`` on ``replica_state`` heartbeats (the compact
serialized cumulative sketch the rollup merges), an ``slo`` dict on
``serve_summary`` (spec, window/breach totals, worst burn, cumulative
sketch percentiles), and the fleet verdict fields on ``fleet_summary``
(``slo_verdict`` pass|fail, ``slo_windows`` / ``slo_breaches`` /
``slo_worst_burn`` / ``slo_worst_window``) the chaos scenarios score.
Without ``--slo`` none of these are emitted — streams are
byte-identical to v13 runs.  v14 is once more a strict superset: every
v1–v13 stream validates unchanged.

Version 15 adds the hot-path overhead stratum (obs/tickprof.py;
``--tick-profile`` on serve.py / train.py — README "Hot-path
profiling"):

``tick_profile``      one per sampled tick/step (every
                      ``--tick-profile-every``-th) — the tick's phase
                      decomposition in milliseconds (serve: admit /
                      dispatch_enqueue / device_wait / harvest /
                      spool_io / telemetry; train: data_wait /
                      dispatch / device / checkpoint / telemetry), the
                      measured wall time, and ``host_gap_ms`` = wall
                      minus the device phase.  Carries a perf_counter
                      ``ts`` so trace_export renders a host-gap
                      counter track.
``overhead_summary``  one per run — per-phase cumulative totals +
                      log-bucket sketch summaries, cumulative wall /
                      device / host-gap milliseconds and the
                      ``host_overhead_frac`` tools/perf_ledger.py
                      regression-gates against PERF_BASELINE.json.

plus idle-spin accounting on ``serve_summary`` (``idle_ticks`` /
``idle_wait_ms`` — producer-driven runs that sleep in ``engine.run``
now show how much wall time was idle) and ``host_overhead_frac`` on
``serve_summary`` and ``replica_state`` heartbeats (fleet_report names
the worst-overhead replica).  Without ``--tick-profile`` only the idle
counters are new; v15 is once more a strict superset: every v1–v14
stream validates unchanged.

Version 16 adds the speculative-decoding ledger on ``serve_summary``
(apex_example_tpu/spec/; ``--speculate K`` on serve.py — README
"Speculative decoding"): ``speculate_k`` / ``draft_kind`` name the
armed configuration, ``tokens_drafted`` / ``tokens_accepted`` /
``tokens_sampled`` count draft lanes fed, draft lanes verified-and-kept
and model-sampled tokens (bonus lanes + plain-path samples), and
``acceptance_rate`` / ``tokens_per_tick`` are the derived headline
ratios (accepted/drafted; output_tokens/compute_steps — the decode-side
metric that breaks the one-token-per-tick wall).  Conservation is
checkable from the summary alone: ``tokens_accepted <= tokens_drafted``
and ``output_tokens == tokens_accepted + tokens_sampled`` (ci_gate
``--spec-stream``).  Emitted ONLY when speculation is armed — an
unarmed run's stream is byte-identical to v15 output, and v16 is once
more a strict superset: every v1–v15 stream validates unchanged.

Version 17 adds the multi-tenant scheduling stratum
(apex_example_tpu/sched/; ``--tenants`` on serve.py / fleet.py —
README "Multi-tenant scheduling & prefix-affinity routing"):

- ``tenant`` on ``request_complete`` / ``request_failed`` / ``shed``
  names the lane the request was filed under;
- ``tenants`` on ``serve_summary`` is the engine's per-tenant
  scheduling ledger (weight, slo_class, admitted_tokens, budget,
  per-status counts), and on ``fleet_summary`` the router's
  per-tenant verdict block (per-status counts, availability, an
  ``slo_verdict`` per tenant with an SLO spec, admitted_tokens /
  budget folded from replica heartbeats);
- ``prefix_keys`` / ``prefix_shared_tokens`` / ``prefix_prompt_tokens``
  on ``replica_state`` advertise the replica's hottest prefix
  chain-key hashes (sched/prefix.py digests, top-N by block refcount)
  and its raw prefix-reuse counters (``--advertise-prefixes``), the
  inputs to the ``prefix_affinity`` router policy;
- ``tenant_admitted`` on ``replica_state`` carries the engine's
  per-tenant admitted-token totals so the router can account budgets
  fleet-wide;
- ``prefix_hit_rate`` on ``fleet_summary`` is the exact fleet-level
  ratio (sum of advertised shared tokens / sum of prompt tokens).

All emitted ONLY when tenancy / prefix advertisement is armed — an
unarmed run's stream is byte-identical to v16 output, and v17 is once
more a strict superset: every v1–v16 stream validates unchanged.

Version 18 adds the live-migration + elastic-pool stratum (ISSUE 20 —
``ServeEngine.extract_live``/``admit_migrated``, drain-without-eviction
and the fleet autoscaler):

``kv_migration``  one per live-migration side: the source engine that
                  snapshotted a MID-FLIGHT request (arena blocks at the
                  committed cursor, generated tokens, sampler state)
                  emits ``direction: "out"`` with ``tokens_generated``;
                  the destination that scattered the payload and
                  resumed decoding emits ``direction: "in"`` (with
                  ``migration_ms`` transit, ``requeued`` deferral
                  episodes, and the same ``redelivered``/``duplicate``
                  lease-crash provenance ``kv_handoff`` carries — the
                  payloads ride the identical leased spool protocol).

plus the migration ledger on ``serve_summary`` (``migrations_out`` /
``migrations_in`` / ``migration_requeued`` / ``migration_duplicates``
/ ``migration_redelivered`` / ``migration_bytes`` / ``migration_ms``
percentiles), ``migrated`` on ``serve_drain`` (a migrating drain ships
its in-flight slots instead of ticking them out — evictions stay 0),
and the fleet-side counters on ``fleet_summary`` (``migrations`` /
``migration_completed`` — uids shipped mid-flight and their eventual
terminals — and ``scale_up_events`` / ``scale_down_events`` from the
elastic pool controller).  All emitted ONLY when migration/autoscale
traffic actually happened — a migration-free run's stream is
byte-identical to v17 output, and v18 is once more a strict superset:
every v1–v17 stream validates unchanged.

``validate_record`` is the single source of truth consumed by
``tools/metrics_lint.py`` and the tier-1 smoke test; extending the schema
means extending the tables here, nowhere else.  (The supervisor carries
a hard-coded copy of SCHEMA_VERSION — resilience/supervisor.py is
jax-free by contract and must not import the package.)
"""

from __future__ import annotations

from typing import Any, Dict, List

SCHEMA_VERSION = 18

_NUM = (int, float)
# v6 cost fields degrade to null where a backend omits the analysis —
# the record still lands, consumers see an explicit null, and a typo'd
# field name is still rejected (unknown fields stay errors).
_NUM_OR_NULL = (int, float, type(None))

# record type -> {field: allowed python types}; None in OPTIONAL means any.
REQUIRED: Dict[str, Dict[str, Any]] = {
    "run_header": {
        "record": str,
        "schema": int,
        "time": _NUM,
        "run_id": str,
        "num_devices": int,
        "process_index": int,
        "platform": str,
        "config": dict,
    },
    "step": {
        "record": str,
        "step": int,
        "epoch": int,
        "loss": _NUM,
        "scale": _NUM,
        "step_time_ms": _NUM,
        "items_per_sec": _NUM,
    },
    "run_summary": {
        "record": str,
        "steps": int,
        "overflow_count": int,
    },
    "bench": {
        "record": str,
        "metric": str,
        "value": _NUM,
        "unit": str,
    },
    "accuracy": {
        "record": str,
        "opt_level": str,
        "top1": _NUM,
    },
    # --- schema v2: diagnostics records (failure-path observability) ---
    "crash_dump": {
        "record": str,
        "time": _NUM,
        "reason": str,
    },
    "stall": {
        "record": str,
        "time": _NUM,
        "seconds_since_step": _NUM,
    },
    "overflow_event": {
        "record": str,
        "time": _NUM,
        "step": int,
        "modules": list,
    },
    # --- schema v3: serving records (serve.py / serve/engine.py) ---
    "request_complete": {
        "record": str,
        "time": _NUM,
        "request_id": str,
        "prompt_tokens": int,
        "output_tokens": int,
        "ttft_ms": _NUM,
        "tpot_ms": _NUM,
        "finish_reason": str,
    },
    "serve_summary": {
        "record": str,
        "time": _NUM,
        "requests": int,
        "output_tokens": int,
        "tokens_per_sec": _NUM,
    },
    # --- schema v4: resilience records (the recover path) ---
    "preemption": {
        "record": str,
        "time": _NUM,
        "signal": str,
        "step": int,
    },
    "restart": {
        "record": str,
        "time": _NUM,
        "attempt": int,
        "exit_code": int,
        "reason": str,
    },
    "resume": {
        "record": str,
        "time": _NUM,
        "attempt": int,
    },
    # --- schema v5: serving-resilience records (serve.py / serve/) ---
    "request_failed": {
        "record": str,
        "time": _NUM,
        "request_id": str,
        "status": str,          # timeout | cancelled | failed | rejected
    },
    "shed": {
        "record": str,
        "time": _NUM,
        "request_id": str,
        "reason": str,          # queue_full
    },
    "serve_drain": {
        "record": str,
        "time": _NUM,
        "signal": str,
    },
    # --- schema v6: compiled-graph cost records (obs/costmodel.py) ---
    "compile_event": {
        "record": str,
        "time": _NUM,
        "name": str,            # the instrumented function's name
        "compile_ms": _NUM,
    },
    "cost_model": {
        "record": str,
        "time": _NUM,
        "name": str,
    },
    # --- schema v9: trace-event records (obs/trace.py; --trace) ---
    "trace_event": {
        "record": str,
        "ph": str,              # B | E | X | i
        "name": str,
        "ts": _NUM,             # perf_counter seconds (monotonic)
    },
    "clock_sync": {
        "record": str,
        "time": _NUM,           # wall clock (time.time())
        "ts": _NUM,             # perf_counter taken back-to-back
    },
    # --- schema v10: fleet records (apex_example_tpu/fleet/; fleet.py) ---
    "route": {
        "record": str,
        "time": _NUM,
        "request_id": str,
        "replica": str,         # the replica the request was handed to
    },
    "replica_state": {
        "record": str,
        "time": _NUM,
        "replica": str,
        "state": str,           # serving|draining|healthy|stalled|
    },                          #   crashed|restarting|stopped
    "fleet_summary": {
        "record": str,
        "time": _NUM,
        "replicas": int,
        "requests": int,
        "availability": _NUM,   # ok / non-drained terminal, fleet-wide
    },
    # --- schema v11: quantization records (apex_example_tpu/quant/) ---
    "quant_event": {
        "record": str,
        "time": _NUM,
        "kind": str,            # weights | kv
        "dtype": str,           # int8 | float8_e4m3 | fp8_e4m3_emulated
    },
    # --- schema v12: disaggregated-serving records (serve/disagg.py) ---
    "kv_handoff": {
        "record": str,
        "time": _NUM,
        "request_id": str,
        "direction": str,       # out (prefill -> transport) | in
        "fill": int,            # tokens of KV in the payload
        "blocks": int,          # arena blocks in the payload
        "payload_bytes": int,   # payload + scale bytes, dtype-accurate
    },
    # --- schema v18: live-migration records (ISSUE 20) ---
    "kv_migration": {
        "record": str,
        "time": _NUM,
        "request_id": str,
        "direction": str,       # out (source -> transport) | in
        "fill": int,            # tokens of committed KV in the payload
        "blocks": int,          # arena blocks in the payload
        "payload_bytes": int,   # payload + scale bytes, dtype-accurate
    },
    # --- schema v14: streaming SLO records (obs/slo.py; --slo) ---
    "slo_window": {
        "record": str,
        "time": _NUM,
        "window": int,          # tumbling-window ordinal, 0-based
        "requests": int,        # terminal events folded this window
        "good": int,            # ok AND every spec'd latency in target
        "bad": int,             # everything else the server owned
        "burn_rate": _NUM,      # bad fraction / (1 - availability)
    },
    "slo_breach": {
        "record": str,
        "time": _NUM,
        "window": int,          # the slo_window that overspent
        "burn_rate": _NUM,      # > 1.0 by definition
        "requests": int,
        "bad": int,
    },
    "fleet_rollup": {
        "record": str,
        "time": _NUM,
        "replicas": int,        # replicas contributing a sketch
        "count": int,           # merged TTFT observations, fleet-wide
    },
    # --- schema v15: hot-path overhead records (obs/tickprof.py) ---
    "tick_profile": {
        "record": str,
        "time": _NUM,
        "ts": _NUM,             # perf_counter at tick start (trace
        "kind": str,            #   clock domain); serve | train
        "tick": int,            # engine tick / train step ordinal
        "wall_ms": _NUM,        # independently measured tick wall time
        "host_gap_ms": _NUM,    # wall - device phase
        "phases": dict,         # phase -> milliseconds (sum == wall
    },                          #   within 1%; perf_ledger enforces)
    "overhead_summary": {
        "record": str,
        "time": _NUM,
        "kind": str,            # serve | train
        "ticks": int,           # ticks folded (every tick, not sampled)
        "wall_ms": _NUM,        # cumulative
        "device_ms": _NUM,      # cumulative device-phase time
        "host_gap_ms": _NUM,    # wall_ms - device_ms
        "host_overhead_frac": _NUM,   # host_gap_ms / wall_ms
        "phases": dict,         # phase -> {count,p50,p90,p99,min,max,
    },                          #   total_ms} sketch summaries
}

OPTIONAL: Dict[str, Dict[str, Any]] = {
    "run_header": {"argv": list, "num_processes": int, "arch": str},
    "step": {
        "grad_norm": _NUM,
        "grads_finite": _NUM,
        "overflow_count": int,
        "top1": _NUM,
        "ppl": _NUM,
        "masked_acc": _NUM,
        "lr": _NUM,
        "time": _NUM,
        "memory": dict,
        "spans": dict,
    },
    "run_summary": {
        "first_step_ms": _NUM,
        "steady_step_ms": _NUM,
        "compile_est_ms": _NUM,
        "items_per_sec": _NUM,
        "time": _NUM,
        "spans": dict,
        # v2: a crashed/killed run's summary is marked, not absent.
        "aborted": bool,
        "abort_reason": str,
        # v4: the supervisor's closing record (tools/supervise.py).
        "restart_count": int,
        "exit_code": int,
        # v6: measured compile totals (obs/costmodel.py) — the
        # first-vs-steady compile_est_ms above becomes a cross-check,
        # not the only source.
        "compile_events": int,
        "compile_ms_total": _NUM,
    },
    "bench": {"vs_baseline": _NUM, "mfu_pct": _NUM, "time": _NUM,
              "config": dict},
    "accuracy": {"seed": int, "eval_loss": _NUM, "final_train_loss": _NUM,
                 "train_seconds": _NUM, "time": _NUM},
    "crash_dump": {
        "run_id": str,
        "step": int,            # last completed step at dump time
        "traceback": str,       # uncaught-exception path
        "thread_stacks": str,   # signal path: all-thread stack dump
        "last_steps": list,     # the flight recorder's bounded ring
        "registry": dict,       # MetricsRegistry.snapshot()
        "memory": dict,         # device_memory_stats() subset
        "env": dict,            # python/platform/jax versions, argv
        "config": dict,         # JSON-safe argparse snapshot
    },
    "stall": {
        "run_id": str,
        "step": int,            # last completed step before the stall
        "deadline_s": _NUM,
        "thread_stacks": str,
        "trace_dir": str,       # set when a one-shot profiler window armed
    },
    "overflow_event": {
        "run_id": str,
        "module_stats": dict,   # {module: {nonfinite, grad_norm}}
        "scale": _NUM,
        "loss": _NUM,
        "mode": str,            # the --numerics-check mode that fired
    },
    "request_complete": {
        "run_id": str,
        "slot": int,            # the slot the request decoded in
        "queue_wait_ms": _NUM,  # arrival -> admission
        "e2e_ms": _NUM,         # arrival -> completion
        "admitted_step": int,   # engine tick provenance (interleaving
        "finished_step": int,   #   audits key on these)
        "temperature": _NUM,
        "top_k": int,
        "tenant": str,          # v17: the scheduling lane (--tenants)
    },
    "serve_summary": {
        "run_id": str,
        "steps": int,           # engine ticks (incl. idle virtual-time)
        "compute_steps": int,   # ticks that ran the decode program
        "slots": int,
        "max_len": int,
        "duration_s": _NUM,
        "occupancy": _NUM,      # mean live-slot fraction per compute step
        "ttft_ms": dict,        # {p50, p95, max} nearest-rank
        "tpot_ms": dict,
        "queue_wait_ms": dict,
        "aborted": bool,
        "abort_reason": str,
        # v5: per-status accounting ("requests" stays the terminal total)
        "completed": int,       # status ok
        "timed_out": int,       # deadline expired (queued or mid-flight)
        "shed": int,            # rejected by admission control
        "cancelled": int,
        "failed": int,          # slot-level exception / token guard
        "drained": int,         # requeued by a graceful drain
        "availability": _NUM,   # ok / every status the server owned
        # v6: KV occupancy — arena-lifetime reserved bytes vs what live
        # requests actually fill, per compute tick.
        "kv_bytes_reserved": int,   # full arena allocation (constant)
        "kv_bytes_live": dict,      # per-tick filled-bytes histogram
        "slot_occupancy": dict,     # per-tick live-slot histogram
        "kv_waste_pct": _NUM,       # v7: 100 * (1 - live / held-block
                                    #   bytes), block-accurate
        # v7: the block-paged KV stratum (serve/slots.py; ISSUE 8)
        "block_size": int,          # tokens per arena block
        "blocks_total": int,        # blocks per layer arena
        "blocks_live": dict,        # per-tick held-blocks histogram
        "kv_bytes_committed": dict,  # per-tick held+reserved bytes
        "prefix_hit_rate": _NUM,    # shared / total prompt tokens
        "cow_copies": int,          # copy-on-write block copies
        "rejected": int,            # unservable, rejected at admission
        # v11: the precision story (quant stratum, ISSUE 13) — the
        # byte gauges above are dtype-accurate against these fields.
        "kv_dtype": str,            # arena payload dtype ("int8" armed)
        "weight_dtype": str,        # weight storage mode/dtype
        "kv_bytes_per_token": int,  # actual (scales included)
        "kv_bytes_per_token_bf16": int,  # bf16-equivalent baseline
        # v12: sharded + disaggregated serving (serve/disagg.py)
        "role": str,                # both | prefill | decode
        "mesh": str,                # "data=D,model=T" when sharded
        "dp": int,                  # mesh data-axis size
        "tp": int,                  # mesh model-axis size
        "handoffs_out": int,        # prefill: requests handed off
        "handoffs_in": int,         # decode: handoffs admitted
        "handoff_requeued": int,    # decode: handoffs that had to wait
                                    #   for free slots/blocks (episodes,
                                    #   not retry attempts)
        "handoff_bytes": int,       # payload bytes moved, this role
        "handoff_ms": dict,         # decode: transit percentiles
        # v13: the crash-safe leased-spool story (ISSUE 15)
        "handoff_duplicates": int,   # idempotent re-admissions acked
        "handoff_redelivered": int,  # uids admitted from a reclaimed
                                     #   or adopted lease
        "handoff_quarantined": int,  # corrupt payloads parked at *.bad
        # v14: the streaming SLO fold (obs/slo.py; --slo) — spec,
        # window/breach totals, worst burn, cumulative sketch
        # percentiles.  Absent without --slo.
        "slo": dict,
        # v15: idle-spin accounting (engine.run idle_wait_s sleeps are
        # now observed) + the cumulative host-overhead fraction from
        # the armed tick profiler (absent without --tick-profile).
        "idle_ticks": int,          # step() calls with nothing live
        "idle_wait_ms": _NUM,       # wall time slept between them
        "host_overhead_frac": _NUM,  # (wall - device) / wall, run-wide
        # v16: the speculative-decoding ledger (spec/; --speculate K).
        # Absent unless speculation armed — unarmed streams stay
        # byte-identical to v15.  Conservation: accepted <= drafted and
        # output_tokens == tokens_accepted + tokens_sampled.
        "speculate_k": int,         # armed draft depth K
        "draft_kind": str,          # proposer name (ngram | none | ...)
        "tokens_drafted": int,      # draft lanes fed for verification
        "tokens_accepted": int,     # draft lanes verified and kept
        "tokens_sampled": int,      # model-sampled tokens (bonus lanes
                                    #   + plain/sampled-path tokens)
        "acceptance_rate": _NUM,    # accepted / drafted (0.0 if none)
        "tokens_per_tick": _NUM,    # output_tokens / compute_steps
        # v17: the per-tenant scheduling ledger (sched/; --tenants).
        # Absent unless tenancy armed — unarmed streams stay
        # byte-identical to v16.
        "tenants": dict,            # name -> {weight, slo_class,
                                    #   admitted_tokens, budget?,
                                    #   per-status counts}
        # v18: the live-migration ledger (ISSUE 20).  Every field gated
        # on actual migration traffic — migration-free streams stay
        # byte-identical to v17.
        "migrations_out": int,      # live slots shipped mid-flight
        "migrations_in": int,       # migrated requests resumed here
        "migration_requeued": int,  # deferred-admission episodes
        "migration_duplicates": int,   # idempotent re-admissions acked
        "migration_redelivered": int,  # uids admitted from a reclaimed
                                       #   or adopted lease
        "migration_bytes": int,     # payload bytes moved, both sides
        "migration_ms": dict,       # in side: transit percentiles
    },
    "preemption": {
        "run_id": str,
        "checkpoint_step": int,  # step of the grace-path final save
        "saved": bool,           # False: no --checkpoint-dir to save to
    },
    "restart": {
        "run_id": str,
        "backoff_s": _NUM,
        "last_step": int,        # tailed from the child's metrics JSONL
        "checkpoint_step": int,  # latest checkpoint at restart time
        # v10: how the child died, as the supervisor saw it — fleet
        # tooling keys on this instead of re-parsing child streams.
        "classification": str,   # preempted | crashed | stall_killed
    },
    "resume": {
        "run_id": str,
        "checkpoint_step": int,  # the step the attempt resumes from
        "resume_dir": str,
    },
    "request_failed": {
        "run_id": str,
        "slot": int,             # only when the request was admitted
        "admitted_step": int,
        "failed_step": int,      # engine tick of the termination
        "prompt_tokens": int,
        "output_tokens": int,    # partial output kept at eviction
        "queue_wait_ms": _NUM,
        "e2e_ms": _NUM,
        "error": str,            # traceback digest (status "failed")
        "tenant": str,           # v17: the scheduling lane (--tenants)
    },
    "shed": {
        "run_id": str,
        "step": int,             # engine tick of the rejection
        "pending": int,          # ARRIVED backlog after the shed (what
        "max_pending": int,      #   the tripped bound actually counts)
        "tenant": str,           # v17: the scheduling lane (--tenants)
    },
    "serve_drain": {
        "run_id": str,
        "step": int,             # tick the drain began
        "in_flight": int,        # live slots at drain start
        "completed": int,        # in-flight that finished during drain
        "evicted": int,          # in-flight deadline-evicted/failed
        "requeued": int,         # queued handed back (status "drained")
        "requeued_ids": list,
        "migrated": int,         # v18: in-flight shipped mid-flight by
                                 #   a migrating drain (evictions == 0)
    },
    "compile_event": {
        "run_id": str,
        "lower_ms": _NUM,        # trace+lower wall time (compile_ms is
        "n_compiles": int,       #   the XLA backend compile alone)
        "lowering_hash": str,    # StableHLO digest: the compile-cache
        "platform": str,         #   identity recompile forensics join on
        # v8: the recompile-cause diff (graftlint HLO stratum) — only on
        # n_compiles >= 2 events: the first divergent op vs the previous
        # lowering of the same name.
        "recompile_cause": str,
    },
    "cost_model": {
        "run_id": str,
        "lowering_hash": str,          # joins to its compile_event
        # cost_analysis(); null where the backend omits the analysis
        "flops": _NUM_OR_NULL,
        "bytes_accessed": _NUM_OR_NULL,
        "transcendentals": _NUM_OR_NULL,
        # memory_analysis(); null where omitted (CPU backend)
        "argument_bytes": _NUM_OR_NULL,
        "output_bytes": _NUM_OR_NULL,
        "temp_bytes": _NUM_OR_NULL,
        "generated_code_bytes": _NUM_OR_NULL,
        # the roofline position at the peak constants below
        "peak_flops": _NUM,
        "hbm_gbps": _NUM,
        "arithmetic_intensity": _NUM,  # flops / bytes_accessed
        "ridge_flops_per_byte": _NUM,  # peak_flops / (hbm_gbps * 1e9)
        "compute_ms": _NUM,            # flops / peak_flops
        "hbm_ms": _NUM,                # bytes_accessed / bandwidth
        "analytic_min_ms": _NUM,       # max(compute_ms, hbm_ms)
        "roofline": str,               # compute-bound | hbm-bound
        "mfu_ceiling_pct": _NUM,       # MFU the intensity admits
    },
    "trace_event": {
        "run_id": str,
        "dur": _NUM,            # X only: span length, perf seconds
        "cat": str,             # coarse category (tick/request/span)
        "tid": str,             # logical thread row within the stream
        "span_id": str,         # stream-local span identity
        "parent_id": str,       # span tree edge (same stream)
        "trace_id": str,        # groups streams into one timeline
        "args": dict,           # slot / blocks / status annotations
    },
    "clock_sync": {
        "run_id": str,
        "trace_id": str,
    },
    # --- schema v10: fleet records (apex_example_tpu/fleet/) ---
    "route": {
        "run_id": str,
        "policy": str,           # round_robin | least_pending | least_kv
        "attempt": int,          # 0 = first dispatch of this uid
        "reason": str,           # dispatch | retry | requeue_drain |
        "from_replica": str,     #   backlog; the replica being left on
    },                           #   a retry/requeue
    "replica_state": {
        "run_id": str,
        "tick": int,             # the replica's engine tick counter
        "pending": int,          # its queued-request backlog
        "blocks_live": int,      # KV arena blocks held (least_kv input)
        "kv_bytes_live": int,    # v12: dtype-accurate KV bytes live —
                                 #   what least_kv prefers when present
        "role": str,             # v13: both | prefill | decode
        "pid": int,              # serve-child pid (chaos scripts signal it)
        "attempt": int,          # supervisor attempt index, when known
        "exit_code": int,        # with state crashed/restarting
        "classification": str,   # preempted | crashed | stall_killed
        "detail": str,
        "slo_sketch": dict,      # v14: compact serialized cumulative
                                 #   TTFT/TPOT sketches (--slo armed) —
                                 #   what fleet_rollup merges
        "host_overhead_frac": _NUM,  # v15: the replica's cumulative
                                     #   host-overhead fraction
                                     #   (--tick-profile armed) —
                                     #   fleet_report ranks these
        # v17: prefix-cache advertisement (--advertise-prefixes) — the
        # hot chain-key digests prefix_affinity routing scores against,
        # plus the raw reuse counters the fleet hit rate is exact over.
        "prefix_keys": list,         # top-N sched/prefix.py digests,
                                     #   hottest (highest refcount) first
        "prefix_shared_tokens": int,  # prompt tokens served from the
                                      #   prefix index, cumulative
        "prefix_prompt_tokens": int,  # prompt tokens admitted, cumulative
        "tenant_admitted": dict,      # v17: tenant -> admitted tokens
                                      #   (--tenants armed)
    },
    # --- schema v11: quantization records (apex_example_tpu/quant/) ---
    "quant_event": {
        "run_id": str,
        "tensors": int,          # leaves quantized (weights kind)
        "kept": int,             # leaves kept high-precision
        "bytes_before": int,
        "bytes_after": int,
        "scale_min": _NUM,       # per-channel/block scale spread —
        "scale_max": _NUM,       #   the error bound's multiplier
        "emulated": bool,        # fp8 without native jnp support
        "block_size": int,       # kv kind: scale granularity (tokens)
        "scale_dtype": str,      # kv kind: block-scale storage dtype
    },
    # --- schema v12: disaggregated-serving records (serve/disagg.py) ---
    "kv_handoff": {
        "run_id": str,
        "kv_dtype": str,         # arena payload dtype in the payload
        "prompt_tokens": int,
        "first_token": int,      # the prefill-side sampled first token
        "ttft_ms": _NUM,         # out only: the REAL first-token
                                 #   latency (measured where the first
                                 #   token was sampled — the decode
                                 #   side's request_complete can only
                                 #   see its own clock domain)
        "queue_wait_ms": _NUM,   # out only: prefill-side queue wait
        "src": str,              # role/replica ids, when known
        "dst": str,
        "handoff_ms": _NUM,      # in only: out-stamp -> admission wall
        "requeued": int,         # in only: deferred-admission count
        # v13 (ISSUE 15): the leased-spool crash-safety story
        "redelivered": int,      # in only: delivery came from a
                                 #   reclaimed/adopted lease
        "duplicate": bool,       # in only: uid already admitted — the
                                 #   ack-crash window closing (acked,
                                 #   nothing scattered twice)
        "spool_file": str,       # quarantine only: the parked payload
        "error": str,            # quarantine only: why it failed
    },
    "fleet_summary": {
        "run_id": str,
        "policy": str,
        "scenario": str,         # rolling_restart | crash_storm | ...
        "verdict": str,          # pass | fail (the scenario's score)
        "duration_s": _NUM,
        "completed": int,        # per-status fleet totals ("requests"
        "failed": int,           #   stays the submitted total)
        "timed_out": int,
        "shed": int,
        "cancelled": int,
        "rejected": int,
        "drained_requeued": int,  # requeue-on-drain handoffs performed
        "retries": int,           # deadline-aware re-dispatches
        "duplicates": int,        # late/duplicate terminal reports ignored
        "lost": int,              # uids with NO terminal status (must be 0)
        "per_replica": dict,      # name -> per-status breakdown
        "routing": dict,          # dispatch counts + balance skew
        # v13 (ISSUE 15): disagg topology + leased-spool accounting
        "prefill_replicas": int,  # role=prefill handles in the fleet
        "decode_replicas": int,   # role=decode handles in the fleet
        "handoffs": int,          # uids parked on the KV spool
        "handoff_redelivered": int,  # terminals from redelivered
                                     #   handoff admissions
        "in_spool": int,          # uids still on the spool at close
                                  #   (counted in lost; must be 0)
        # v14 (ISSUE 16): the fleet SLO verdict — event-count tumbling
        # windows over the router's terminal feed, scored against the
        # --slo spec.  Absent without --slo.
        "slo_verdict": str,       # pass | fail (any breached window)
        "slo_windows": int,       # windows scored (trailing partial in)
        "slo_breaches": int,      # windows with burn_rate > 1.0
        "slo_worst_burn": _NUM,   # max window burn rate
        "slo_worst_window": int,  # its 0-based index (first on ties)
        # v17 (ISSUE 19): the multi-tenant verdict block + fleet-level
        # prefix reuse.  Absent unless tenancy / prefix advertisement
        # is armed.
        "tenants": dict,          # name -> {per-status counts,
                                  #   availability, slo_verdict?,
                                  #   admitted_tokens?, budget?}
        "prefix_hit_rate": _NUM,  # sum advertised shared / prompt
                                  #   tokens across replicas
        # v18 (ISSUE 20): live migration + elastic pools.  Absent
        # unless migrations/autoscaling actually happened.
        "migrations": int,        # uids shipped mid-flight (out events)
        "migration_completed": int,  # migrated uids that reached a
                                     #   terminal status afterwards
        "migration_redelivered": int,  # terminals from redelivered
                                       #   migration admissions
        "rebalance_migrations": int,  # migrations the router's
                                      #   KV-pressure policy asked for
        "scale_up_events": int,   # elastic-pool replica spawns
        "scale_down_events": int,  # elastic-pool replica retirements
    },
    # --- schema v14: streaming SLO records (obs/slo.py; --slo) ---
    "slo_window": {
        "run_id": str,
        "counts": dict,          # terminal counts by status (drained
                                 #   included — outside good/bad)
        "ttft_ms": dict,         # window sketch percentile estimates
        "tpot_ms": dict,         #   ({count,p50,p90,p99,min,max}),
        "queue_wait_ms": dict,   #   ok completions only
        "ticks": int,            # engine ticks folded (serve side)
        "occupancy": _NUM,       # mean live-slot fraction over ticks
        "blocks_live": int,      # latest KV gauge snapshot in-window
        "kv_bytes_live": int,
    },
    "slo_breach": {
        "run_id": str,
        "good": int,
        "budget": _NUM,          # the error budget (1 - availability)
    },
    "fleet_rollup": {
        "run_id": str,
        "ttft_ms": dict,         # merged-sketch percentile estimates
        "tpot_ms": dict,
        "per_replica": dict,     # name -> {count, p50}
        "skew": _NUM,            # max p50 / median p50 (>= 2 replicas)
        "straggler": str,        # the max-p50 replica's name
    },
    # --- schema v18: live-migration records (ISSUE 20) ---
    "kv_migration": {
        "run_id": str,
        "kv_dtype": str,         # arena payload dtype in the payload
        "prompt_tokens": int,
        "tokens_generated": int,  # generated tokens riding the payload
                                  #   (0: a mid-prefill migration)
        "src": str,              # role/replica ids, when known
        "dst": str,
        "migration_ms": _NUM,    # in only: out-stamp -> admission wall
        "requeued": int,         # in only: deferred-admission count
        "redelivered": int,      # in only: delivery came from a
                                 #   reclaimed/adopted lease
        "duplicate": bool,       # in only: uid already admitted — the
                                 #   ack-crash window closing (acked,
                                 #   nothing scattered twice)
        "tenant": str,           # the scheduling lane, when tagged
        "spool_file": str,       # quarantine only: the parked payload
        "error": str,            # quarantine only: why it failed
    },
    # --- schema v15: hot-path overhead records (obs/tickprof.py) ---
    "tick_profile": {
        "run_id": str,
    },
    "overhead_summary": {
        "run_id": str,
        "sample_every": int,     # tick_profile sampling stride
        "sampled": int,          # tick_profile records emitted
        "wall": dict,            # per-tick wall-time sketch summary
        "host_gap": dict,        # per-tick host-gap sketch summary
    },
}


def validate_record(rec: Any) -> List[str]:
    """Errors for one parsed JSONL record (empty list == valid).

    Unknown fields are rejected: the schema is the contract log-scraping
    tools depend on, and a silently-passing typo'd field would fork it.
    """
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, expected object"]
    kind = rec.get("record")
    if kind not in REQUIRED:
        return [f"unknown record type {kind!r} "
                f"(expected one of {sorted(REQUIRED)})"]
    errors = []
    required, optional = REQUIRED[kind], OPTIONAL.get(kind, {})
    for field, types in required.items():
        if field not in rec:
            errors.append(f"{kind}: missing required field {field!r}")
        elif not isinstance(rec[field], types) or isinstance(rec[field],
                                                             bool):
            errors.append(f"{kind}: field {field!r} is "
                          f"{type(rec[field]).__name__}, expected "
                          f"{types}")
    for field, value in rec.items():
        if field in required:
            continue
        if field not in optional:
            errors.append(f"{kind}: unknown field {field!r}")
        elif optional[field] is not None and not isinstance(value,
                                                            optional[field]):
            errors.append(f"{kind}: field {field!r} is "
                          f"{type(value).__name__}, expected "
                          f"{optional[field]}")
    return errors


def validate_stream(records) -> List[str]:
    """Validate an iterable of parsed records as one run's stream: per-
    record checks plus the stream invariants (header first, at most one
    header/summary)."""
    errors: List[str] = []
    headers = summaries = 0
    for n, rec in enumerate(records):
        for e in validate_record(rec):
            errors.append(f"line {n + 1}: {e}")
        kind = rec.get("record") if isinstance(rec, dict) else None
        if kind == "run_header":
            headers += 1
            if n != 0:
                errors.append(f"line {n + 1}: run_header must be the first "
                              "record")
        elif kind == "run_summary":
            summaries += 1
    if headers > 1:
        errors.append(f"{headers} run_header records (expected at most 1)")
    if summaries > 1:
        errors.append(f"{summaries} run_summary records (expected at most 1)")
    return errors
