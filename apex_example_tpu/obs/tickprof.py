"""Hot-path overhead attribution — pure stdlib, importable without jax.

Splits every serve tick / train step into named phases and folds each
phase into the obs/slo.py log-bucket sketches (ISSUE 17), so the
question ROADMAP item 5 will be judged on — "host-side gap between
device spans -> ~0" — is measurable before anyone refactors the loop.

Phases
------
A serve tick (serve/engine.py ``step``) decomposes into::

    admit             expire/shed/deadline-evict + queue admission
    dispatch_enqueue  host marshalling + handing the step to the
                      runtime (up to the point the compiled call
                      returns its unresolved outputs)
    device_wait       an explicit ``jax.block_until_ready`` boundary
                      the engine inserts ONLY when armed — the first
                      time enqueue cost and device execution are
                      separable (on CPU jax dispatch is synchronous,
                      so device_wait reads ~0 and the device time
                      hides in dispatch_enqueue; on a real TPU the
                      split is the whole point — see README)
    harvest           per-slot token handling, eviction, completion
    spool_io          handoff spool writes inside harvest (measured
                      around ``handoff_sink`` and subtracted from
                      harvest so disagg IO is not mistaken for
                      scheduler cost)
    telemetry         gauge emission, SLO fold, tracer bookkeeping

and a train step (train.py main loop) into::

    data_wait   batch_fn / input pipeline
    dispatch    the compiled train-step call up to its return
    device      explicit block_until_ready on state + metrics
    telemetry   emitter.on_step (blocking metric fetch) + printing
    checkpoint  the save-every-steps window (0.0 when skipped)

The caller measures ``wall_ms`` independently (one perf_counter pair
around the whole tick) and passes the phases it timed; because the
engine's boundaries are contiguous timestamps the phase sum telescopes
to the wall time — ``tools/perf_ledger.py`` enforces agreement within
1% as a tamper check.

Records
-------
``tick_profile``      one per sampled tick (every ``sample_every``-th;
                      sampling bounds stream growth at high tick
                      rates) — per-phase milliseconds, the tick wall
                      time and its ``host_gap_ms`` (wall minus the
                      device phase).  Carries a perf_counter ``ts`` so
                      trace_export can render a host-gap counter track
                      against the clock_sync anchor.
``overhead_summary``  one per run — per-phase cumulative totals +
                      sketch summaries (count/p50/p90/p99/min/max),
                      the cumulative ``host_gap_ms`` and the
                      ``host_overhead_frac`` = host_gap / wall that
                      replica heartbeats advertise and fleet_report
                      ranks.

Self-contained BY CONTRACT (the obs/slo.py pattern): stdlib-only, so
thin tools load it by FILE PATH without executing the jax-carrying
package ``__init__``.  graftlint's jax-free rule names it in
CONTRACT_FILES; keep it that way.  The sketch helpers come from
obs/slo.py — imported relatively when the package is live, loaded by
file path when this module itself was file-path-loaded.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

try:
    from .slo import (DEFAULT_ALPHA, sketch_add, sketch_new,
                      sketch_summary)
except ImportError:                      # file-path load: no package
    import importlib.util
    import os

    def _load_slo():
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "slo.py")
        spec = importlib.util.spec_from_file_location("_tickprof_slo",
                                                      path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    _slo = _load_slo()
    DEFAULT_ALPHA = _slo.DEFAULT_ALPHA
    sketch_add = _slo.sketch_add
    sketch_new = _slo.sketch_new
    sketch_summary = _slo.sketch_summary

SERVE_PHASES = ("admit", "dispatch_enqueue", "device_wait", "harvest",
                "spool_io", "telemetry")
TRAIN_PHASES = ("data_wait", "dispatch", "device", "checkpoint",
                "telemetry")

# The phase whose time is DEVICE time; everything else is host
# overhead.  host_gap_ms = wall - this phase.
DEVICE_PHASE = {"serve": "device_wait", "train": "device"}

DEFAULT_SAMPLE_EVERY = 16


class TickProfiler:
    """Per-tick phase accounting + cumulative sketches.

    ``observe_tick(ts, wall_ms, **phase_ms)`` folds one tick; every
    ``sample_every``-th call emits a ``tick_profile`` record through
    ``emit`` (a JsonlSink.write or None).  ``summary_record()`` builds
    the closing ``overhead_summary``.
    """

    def __init__(self, kind: str = "serve",
                 sample_every: int = DEFAULT_SAMPLE_EVERY,
                 emit: Optional[Callable[[Dict[str, Any]], Any]] = None,
                 run_id: Optional[str] = None,
                 alpha: float = DEFAULT_ALPHA):
        if kind not in DEVICE_PHASE:
            raise ValueError(f"kind must be serve|train, got {kind!r}")
        if sample_every < 1:
            raise ValueError(f"sample_every must be >= 1, "
                             f"got {sample_every}")
        self.kind = kind
        self.phases = (SERVE_PHASES if kind == "serve"
                       else TRAIN_PHASES)
        self.device_phase = DEVICE_PHASE[kind]
        self.sample_every = int(sample_every)
        self.emit = emit
        self.run_id = run_id
        self.ticks = 0
        self.sampled = 0
        self.wall_ms = 0.0
        self._totals = {p: 0.0 for p in self.phases}
        self._sk = {p: sketch_new(alpha) for p in self.phases}
        self._wall_sk = sketch_new(alpha)
        self._gap_sk = sketch_new(alpha)

    # ------------------------------------------------------------ fold

    def observe_tick(self, ts: float, wall_ms: float,
                     **phase_ms: float) -> Optional[Dict[str, Any]]:
        """Fold one tick.  ``ts``: perf_counter at tick start (the
        trace clock domain); ``wall_ms``: the tick's independently
        measured wall time; keyword args: per-phase milliseconds
        (missing phases count 0.0, unknown phases raise).  Returns the
        emitted ``tick_profile`` record on sampled ticks, else None."""
        unknown = set(phase_ms) - set(self.phases)
        if unknown:
            raise ValueError(f"unknown phase(s) {sorted(unknown)}; "
                             f"{self.kind} phases are {self.phases}")
        wall = float(wall_ms)
        self.wall_ms += wall
        sketch_add(self._wall_sk, wall)
        tick_phases: Dict[str, float] = {}
        for p in self.phases:
            v = float(phase_ms.get(p, 0.0))
            tick_phases[p] = v
            self._totals[p] += v
            sketch_add(self._sk[p], v)
        gap = wall - tick_phases[self.device_phase]
        sketch_add(self._gap_sk, gap)
        tick = self.ticks
        self.ticks += 1
        if self.emit is None or tick % self.sample_every:
            return None
        self.sampled += 1
        rec = {
            "record": "tick_profile",
            "time": time.time(),
            "ts": float(ts),
            "kind": self.kind,
            "tick": tick,
            "wall_ms": wall,
            "host_gap_ms": gap,
            "phases": tick_phases,
        }
        if self.run_id is not None:
            rec["run_id"] = self.run_id
        self.emit(rec)
        return rec

    # ------------------------------------------------------- accessors

    def device_ms(self) -> float:
        """Cumulative device-phase milliseconds."""
        return self._totals[self.device_phase]

    def host_gap_ms(self) -> float:
        """Cumulative wall minus device-phase milliseconds."""
        return self.wall_ms - self.device_ms()

    def host_overhead_frac(self) -> float:
        """host_gap / wall over the whole run (0.0 before any tick)."""
        if self.wall_ms <= 0.0:
            return 0.0
        return self.host_gap_ms() / self.wall_ms

    def phase_summary(self) -> Dict[str, Dict[str, Any]]:
        """phase -> sketch summary + cumulative ``total_ms``."""
        out: Dict[str, Dict[str, Any]] = {}
        for p in self.phases:
            s = sketch_summary(self._sk[p])
            s["total_ms"] = self._totals[p]
            out[p] = s
        return out

    def summary_record(self) -> Dict[str, Any]:
        """The closing ``overhead_summary`` record (schema v15)."""
        rec = {
            "record": "overhead_summary",
            "time": time.time(),
            "kind": self.kind,
            "ticks": self.ticks,
            "wall_ms": self.wall_ms,
            "device_ms": self.device_ms(),
            "host_gap_ms": self.host_gap_ms(),
            "host_overhead_frac": self.host_overhead_frac(),
            "phases": self.phase_summary(),
            "sample_every": self.sample_every,
            "sampled": self.sampled,
            "wall": sketch_summary(self._wall_sk),
            "host_gap": sketch_summary(self._gap_sk),
        }
        if self.run_id is not None:
            rec["run_id"] = self.run_id
        return rec
