"""Stall watchdog: a hung run emits evidence instead of nothing.

A deadlocked collective, a wedged device tunnel, or a host-side hang
leaves the telemetry stream silent — the worst possible signal.  The
watchdog is a daemon thread that watches the gap since the last completed
step; when the gap exceeds a configurable deadline it

- dumps every thread's python stack (what IS the host waiting on?),
- writes a schema-valid ``stall`` record to the run's JSONL sink, and
- optionally arms a one-shot profiler trace (``trace_dir``), so the
  device timeline of the stall itself gets captured.

One stall record per gap: after firing, the watchdog stays quiet until a
step completes (which also stops the armed trace — the "window" is
stall-start to first-recovered-step), then re-arms for the next gap.  A
clean ``close()`` disarms it so a run that simply *ends* never reads as
a stall.

The deadline includes the first step's trace+compile time — size it
accordingly (or start the clock late by calling ``notify_step(0)`` after
warmup).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from apex_example_tpu.obs import metrics as metrics_lib
from apex_example_tpu.obs.flight import format_thread_stacks


class StallWatchdog:
    """Host-side stall detector bound to a run's JSONL sink.

    Wire-up shape (what train.make_telemetry does)::

        watchdog = StallWatchdog(sink, deadline_s=120)
        watchdog.start()
        emitter.add_observer(watchdog.on_record)   # per-step heartbeat
        ...
        watchdog.close()                           # clean exit: disarm
    """

    def __init__(self, sink: metrics_lib.JsonlSink, deadline_s: float,
                 run_id: Optional[str] = None,
                 trace_dir: Optional[str] = None,
                 poll_s: Optional[float] = None,
                 clock=time.perf_counter):
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        self.sink = sink
        self.deadline_s = float(deadline_s)
        self.run_id = run_id
        self.trace_dir = trace_dir
        self._clock = clock
        # Poll fast enough to resolve the deadline without busy-waiting.
        self._poll_s = poll_s if poll_s is not None \
            else min(max(self.deadline_s / 4.0, 0.01), 1.0)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._last = clock()                    # guarded-by: _lock
        self._last_step = 0                     # guarded-by: _lock
        self._fired = False                     # guarded-by: _lock
        self._tracing = False                   # guarded-by: _lock
        self._trace_used = False                # watchdog thread only
        self.stall_count = 0                    # guarded-by: _lock
        self._thread = threading.Thread(target=self._run,
                                        name="apex-stall-watchdog",
                                        daemon=True)

    def start(self) -> None:
        self._thread.start()

    # ------------------------------------------------------- heartbeat

    def on_record(self, record, metrics=None) -> None:
        """TelemetryEmitter observer form of :meth:`notify_step`."""
        if record.get("record") == "step":
            self.notify_step(int(record.get("step", 0)))

    def notify_step(self, step: int) -> None:
        """A step completed: reset the deadline clock and re-arm."""
        with self._lock:
            self._last = self._clock()
            self._last_step = step
            self._fired = False
            was_tracing, self._tracing = self._tracing, False
        if was_tracing:
            self._stop_trace()

    # ---------------------------------------------------------- thread

    def _run(self) -> None:
        while not self._stop.wait(self._poll_s):
            # Check and mark under ONE lock hold: setting _fired outside
            # the gap check would let a notify_step landing in between
            # have its re-arm clobbered, permanently disarming the
            # watchdog for the NEXT (real) stall.
            with self._lock:
                gap = self._clock() - self._last
                step = self._last_step
                fire = gap >= self.deadline_s and not self._fired
                if fire:
                    self._fired = True
                    # Count under the SAME lock hold as the fire
                    # decision: the watchdog thread writes this while
                    # the main thread polls it, and the unguarded
                    # increment was graftlint's first lock-discipline
                    # true positive (ISSUE 9).
                    self.stall_count += 1
            if fire:
                self._emit_stall(gap, step)

    def _emit_stall(self, gap: float, step: int) -> None:
        rec = {"record": "stall",
               "time": metrics_lib.now(),
               "seconds_since_step": round(gap, 3),
               "step": int(step),
               "deadline_s": self.deadline_s,
               "thread_stacks": format_thread_stacks()}
        if self.run_id:
            rec["run_id"] = self.run_id
        if self.trace_dir and not self._trace_used:
            # One-shot profiler window: stall-start .. first recovered
            # step (or close()).  Never re-armed — a flapping run must
            # not accrete trace directories.
            try:
                import jax
                jax.profiler.start_trace(self.trace_dir)
            except Exception:
                pass
            else:
                with self._lock:
                    self._tracing = True
                self._trace_used = True
                rec["trace_dir"] = self.trace_dir
        self.sink.write(rec)

    def _stop_trace(self) -> None:
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception:  # pragma: no cover
            pass

    # ----------------------------------------------------------- close

    def close(self) -> None:
        """Clean-exit disarm: stop the thread; a run that ends is not a
        stall.  Stops a still-armed trace so the capture isn't lost."""
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=2.0)
        with self._lock:
            was_tracing, self._tracing = self._tracing, False
        if was_tracing:
            self._stop_trace()
