"""apex_example_tpu.resilience — the fault-tolerance stratum.

PR 2's diagnostics stratum (obs/flight.py) made the failure path
*observable*; this package makes it *survivable*.  Production TPU fleets
run on interruptible capacity, so preemption and restart are the steady
state, not the exception — three pillars turn "observe the failure" into
"absorb the failure":

- :mod:`~apex_example_tpu.resilience.preemption`  SIGTERM/SIGUSR1 grace
  path: the handler only sets a flag; the train loop notices it at the
  next step boundary, saves a final checkpoint, emits a ``preemption``
  record and exits ``EX_TEMPFAIL`` (75) — resumable, not broken.
- :mod:`~apex_example_tpu.resilience.supervisor`  auto-resume supervisor
  (pure stdlib, **jax-free by contract** — it must run on hosts where
  jax is broken; ``tools/supervise.py`` is its CLI): runs train.py as a
  child, restarts on preemption/crash with exponential backoff, rewrites
  ``--resume`` each attempt, and emits ``restart``/``resume`` records.
- :mod:`~apex_example_tpu.resilience.faults`  deterministic fault
  injection (``--inject-fault kind@step``): crash / SIGTERM-self / hang /
  grad-NaN at a chosen step, so the whole loop — fault → forensics →
  graceful save → supervised restart → exact continuation — is testable
  end-to-end in tier-1.  The serve path (serve.py; ISSUE 5) accepts the
  same kinds plus ``slot_fail`` (``SERVE_KINDS``) at engine-tick
  granularity — sigterm drives the graceful drain, slot_fail the
  slot-isolation path — and the disagg handoff drills
  (``HANDOFF_KINDS``, ISSUE 15) at send/admit granularity: torn
  payloads, the ack-crash window, duplicate delivery, a lost close
  sentinel.

``supervisor`` is importable here for in-package callers, but the CLI
loads it by file path (the package ``__init__`` pulls jax).
"""

from apex_example_tpu.resilience.faults import (HANDOFF_KINDS, KINDS,
                                                SERVE_KINDS,
                                                FaultInjected, FaultPlan)
from apex_example_tpu.resilience.preemption import (EX_TEMPFAIL,
                                                    PreemptionHandler)
from apex_example_tpu.resilience.supervisor import Supervisor

__all__ = ["EX_TEMPFAIL", "FaultInjected", "FaultPlan", "HANDOFF_KINDS",
           "KINDS", "PreemptionHandler", "SERVE_KINDS", "Supervisor"]
