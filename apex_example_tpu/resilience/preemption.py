"""Preemption grace path: turn SIGTERM into a clean, resumable exit.

Without this module a preempted run dies mid-step: the flight recorder
(obs/flight.py) writes a ``crash_dump``, marks the summary
``aborted: true`` and re-delivers the signal — exit status 143, forensics
but no survival.  With ``--preempt-grace`` the handler here only SETS A
FLAG; the training loop polls it at the next step boundary and runs the
grace sequence itself, outside signal context:

1. join any pending async orbax write, save a final checkpoint (with the
   host-state sidecar, so resume is exact — utils/checkpoint.py);
2. emit a ``preemption`` record (schema v4) through the telemetry sink —
   NOT a crash_dump, and the run summary stays un-aborted;
3. return ``EX_TEMPFAIL`` (75), the sysexits.h "temporary failure, retry"
   status, so a supervisor (resilience/supervisor.py) knows the run is
   resumable rather than broken.

Coordination with the flight recorder: both want SIGTERM.  The handler
takes ownership explicitly via ``FlightRecorder.release_signal`` — the
recorder restores its saved previous disposition and forgets the signal,
then this handler installs over that — so close order never matters and
a real crash (exception, SIGSEGV, atexit) still reaches the recorder's
hooks.  SIGUSR1 rides along for schedulers that send it as the
preemption notice (SLURM ``--signal``, borg-style warning signals).
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Optional, Tuple

# sysexits.h EX_TEMPFAIL: "temporary failure; the user is invited to
# retry".  os.EX_TEMPFAIL where the platform defines it — the literal is
# the contract (the supervisor matches on 75, possibly on another host).
EX_TEMPFAIL = getattr(os, "EX_TEMPFAIL", 75)

DEFAULT_SIGNALS: Tuple[int, ...] = (signal.SIGTERM, signal.SIGUSR1)


class PreemptionHandler:
    """Flag-only signal handler for the graceful-preemption path.

    Usage shape (what train.py's loops do)::

        preempt = PreemptionHandler(recorder=recorder)   # recorder may be None
        preempt.install()
        for step ...:
            ...train...
            if preempt.preempted:
                break                       # grace sequence runs here
        preempt.close()                     # restore dispositions

    The handler is async-signal-minimal: it records the signal name and a
    timestamp, nothing else — no IO, no allocation-heavy work.  Repeat
    deliveries while the flag is already set are ignored (cloud
    preemption escalates to SIGKILL on its own schedule; a second SIGTERM
    must not turn a grace save into a crash).
    """

    def __init__(self, signals: Tuple[int, ...] = DEFAULT_SIGNALS,
                 recorder=None):
        self.signals = tuple(signals)
        self.recorder = recorder
        self._prev = {}
        self._installed = False
        self._closed = False
        self._preempted = False
        self.signal_name: Optional[str] = None
        self.preempt_time: Optional[float] = None

    # ------------------------------------------------------------ state

    @property
    def preempted(self) -> bool:
        return self._preempted

    @property
    def installed(self) -> bool:
        return self._installed

    # ------------------------------------------------------------ hooks

    def install(self) -> None:
        """Arm the grace handlers.  Signal handlers only install from the
        main thread (CPython's constraint); off the main thread this is a
        no-op and ``installed`` stays False."""
        if self._installed or self._closed:
            return
        if threading.current_thread() is not threading.main_thread():
            return
        for sig in self.signals:
            if self.recorder is not None:
                # Explicit handover: the recorder restores its saved
                # previous disposition and forgets the signal, so its
                # close() can no longer clobber ours.
                self.recorder.release_signal(sig)
            try:
                self._prev[sig] = signal.signal(sig, self._on_signal)
            except (ValueError, OSError):  # pragma: no cover
                continue
        self._installed = bool(self._prev)

    def close(self) -> None:
        """Restore the previous dispositions (the recorder's original
        previous handler where a handover happened — not the recorder's,
        which released ownership at install)."""
        if self._closed:
            return
        self._closed = True
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):  # pragma: no cover
                pass
        self._prev.clear()

    # ----------------------------------------------------- hook target

    def _on_signal(self, signum, frame) -> None:
        if self._preempted:
            return
        self._preempted = True
        self.signal_name = signal.Signals(signum).name
        self.preempt_time = time.time()
