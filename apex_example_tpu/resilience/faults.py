"""Deterministic fault injection (``--inject-fault kind@step``).

The resilience loop — fault → forensics → graceful save → supervised
restart → exact continuation — is only trustworthy if every stage is
testable, and real faults don't arrive on cue.  A ``FaultPlan`` fires a
chosen fault at an exact global step:

``crash``    raise :class:`FaultInjected` (a RuntimeError) after the
             step completes — exercises the flight recorder's
             exception path (crash_dump + aborted summary) and the
             supervisor's crash-restart backoff.
``sigterm``  ``os.kill(self, SIGTERM)`` after the step completes — the
             preemption drill: under ``--preempt-grace`` the loop
             notices the flag at the next boundary and runs the grace
             save; without it, the flight recorder's 143 path.
``hang``     block in ``time.sleep`` after the step completes —
             exercises the stall watchdog (``--stall-timeout``) and the
             supervisor's ``--stall-kill``.
``nan``      poison every *floating* leaf of the step's input batch with
             NaN — grads go non-finite, exercising the overflow/
             numerics provenance path (``--numerics-check``).  Requires
             the batch to carry at least one float leaf (images, MLM
             label weights); an int-only token batch is rejected at
             fire time.  On the SERVE path (serve.py) the same kind
             instead degenerates the tick's sampled tokens, exercising
             the engine's NaN/degenerate-logits guard.

Serve-only kind (``SERVE_KINDS``; serve.py accepts it, train.py keeps
rejecting it):

``slot_fail``  raise :class:`FaultInjected` inside ONE slot's harvest at
               the chosen engine tick — exercises the serving engine's
               failure isolation: exactly that slot's request fails
               (``request_failed`` record), every other request is
               token-identical to a fault-free run.

Handoff kinds (``HANDOFF_KINDS``, ISSUE 15; serve.py routes them to the
disaggregated-serving handoff path — serve/disagg.py — instead of the
engine, and the step is the 1-based ordinal of the named OPERATION, not
an engine tick):

``handoff_torn``          prefill side: the Nth ``FileTransport.send``
                          writes a truncated spool payload — the decode
                          worker must QUARANTINE it (``*.bad`` + a
                          ``kv_handoff`` direction "quarantine" record)
                          and keep ticking.
``handoff_crash_preack``  decode side: crash between the Nth successful
                          ``admit_handoff`` and its ack — the ack-crash
                          window.  The claim stays on disk, so the
                          restarted worker (or a lease-expiry peer)
                          redelivers; the engine's seen-set detects the
                          duplicate and acks it without a second
                          scatter.
``handoff_dup``           decode side: redeliver the Nth admitted
                          handoff a second time — the pure duplicate-
                          delivery drill (seen-set path, no crash).
``sentinel_lost``         prefill side: ``FileTransport.close`` never
                          writes the ``close.json`` sentinel — the
                          producer-died shape a decode worker's
                          ``--handoff-idle-timeout`` must resolve
                          instead of spinning forever.

Steps are 1-based **global** steps (engine ticks on the serve path) and
fire exactly once — on equality for the training kinds (a resumed run
whose restored step is already past the fault step never re-fires,
which is precisely what makes "restart then run to completion"
testable), and at the first tick ``>=`` the target for the
caller-handled serve kinds (``due()``/``take()``: a slot-level drill
landing on a tick that cannot express it — idle, or every slot still
prefilling — defers rather than vanishing; the serve path has no
resume, so late-firing never double-fires).  Handoff drills on a
supervised decode worker MUST be stripped from restart attempts
(``tools/supervise.py --drop-flag-on-restart=--inject-fault``): the
restarted worker replays the spool from its claim set, so an
operation-ordinal drill would re-fire every attempt, exactly like the
exact-tick serve drills.
"""

from __future__ import annotations

import os
import signal
import time

KINDS = ("crash", "sigterm", "hang", "nan")
# Disagg handoff drills (ISSUE 15): fired by the handoff transport /
# decode drive loop at the Nth send/admit (serve/disagg.py), never by
# the engine tick loop.
HANDOFF_KINDS = ("handoff_torn", "handoff_crash_preack", "handoff_dup",
                 "sentinel_lost")
# serve.py additionally accepts slot_fail (slot-level failure isolation)
# and the handoff drills; train.py keeps validating against the
# training KINDS.
SERVE_KINDS = KINDS + ("slot_fail",) + HANDOFF_KINDS

# Long enough that a hung step is indistinguishable from a real wedge to
# every consumer (watchdog, supervisor), bounded so an unsupervised run
# still terminates eventually.
HANG_SECONDS = 3600.0


class FaultInjected(RuntimeError):
    """The injected-crash exception ('crash' kind).  A RuntimeError
    subclass so generic crash handling treats it as any other failure;
    its own type so tests and log-readers can tell drill from disease."""


class FaultPlan:
    """One fault, one step, fires once.  ``kinds`` is the accepted set —
    training loops use the default ``KINDS``, serve.py passes
    ``SERVE_KINDS`` (adds slot_fail)."""

    def __init__(self, kind: str, step: int, hang_s: float = HANG_SECONDS,
                 kinds=KINDS):
        if kind not in kinds:
            raise ValueError(f"unknown fault kind {kind!r} "
                             f"(expected one of {kinds})")
        if step < 1:
            raise ValueError(f"fault step must be >= 1, got {step}")
        self.kind = kind
        self.step = int(step)
        self.hang_s = hang_s
        self.fired = False

    @classmethod
    def parse(cls, spec: str, kinds=KINDS) -> "FaultPlan":
        """``kind@step`` — e.g. ``sigterm@12``."""
        kind, sep, step_s = spec.partition("@")
        if not sep or not kind or not step_s:
            raise ValueError(f"--inject-fault {spec!r}: expected kind@step "
                             f"(kinds: {', '.join(kinds)})")
        try:
            step = int(step_s)
        except ValueError:
            raise ValueError(f"--inject-fault {spec!r}: step {step_s!r} is "
                             "not an integer")
        return cls(kind, step, kinds=kinds)

    def __repr__(self) -> str:
        return f"FaultPlan({self.kind}@{self.step})"

    # ------------------------------------------------------------- fire

    def maybe_poison(self, step: int, batch):
        """'nan' kind, called with the 1-based global step the batch is
        ABOUT to be consumed by: returns the batch with every floating
        leaf replaced by NaN at the fault step, unchanged otherwise."""
        if self.kind != "nan" or self.fired or step != self.step:
            return batch
        self.fired = True
        import jax
        import jax.numpy as jnp

        poisoned = [False]

        def poison(leaf):
            x = jnp.asarray(leaf)
            if jnp.issubdtype(x.dtype, jnp.floating):
                poisoned[0] = True
                return jnp.full_like(x, jnp.nan)
            return leaf

        batch = jax.tree_util.tree_map(poison, batch)
        if not poisoned[0]:
            raise FaultInjected(
                f"nan fault at step {self.step}: the batch carries no "
                "floating-point leaf to poison (int-only token batches "
                "cannot carry NaN — use the image or MLM workloads)")
        return batch

    def due(self, step: int) -> bool:
        """Caller-handled kinds (the serve engine's ``nan`` token
        degeneration and ``slot_fail`` isolation): armed and reached —
        ``>=`` rather than ``==``, because a slot-level fault scheduled
        on an idle or all-prefill tick must fire at the next tick that
        CAN express it (the serve path has no resume, so late-firing
        never double-fires).  The caller consumes it with take()."""
        return not self.fired and step >= self.step

    def take(self) -> None:
        """Consume a due() fault — exactly-once is the caller's pairing
        of due() and take()."""
        self.fired = True

    def maybe_fire(self, step: int) -> None:
        """crash/sigterm/hang kinds, called with the 1-based global step
        that JUST completed.  Fires after the step's telemetry record is
        emitted, so forensics always hold the last good step."""
        if self.kind not in ("crash", "sigterm", "hang") or self.fired \
                or step != self.step:
            return
        self.fired = True
        if self.kind == "crash":
            raise FaultInjected(f"injected crash at step {self.step}")
        if self.kind == "sigterm":
            os.kill(os.getpid(), signal.SIGTERM)
            return
        # hang: one opaque block, like a wedged collective — the stall
        # watchdog's stacks will point here.
        time.sleep(self.hang_s)
