"""Auto-resume supervisor: keep a training run alive across preemptions.

Pure stdlib ON PURPOSE — the supervisor's job is to restart training on
hosts where training just died, including deaths caused by a broken jax
install, so it must not import jax (or anything that transitively does;
graftlint's static ``jax-free`` rule proves this over the whole import
closure — tools/graftlint/imports.py, ISSUE 9).
``tools/supervise.py`` is the CLI; it loads this file by path so even
the package ``__init__`` (which pulls jax) is never imported.

Contract with the child (train.py):

- exit 0              done — the supervisor exits 0;
- exit 75             graceful preemption (``EX_TEMPFAIL``, the
                      ``--preempt-grace`` path): restart promptly
                      (``preempt_delay_s``, default 0 — the capacity is
                      back when the scheduler restarts us);
- any other exit      crash: restart with exponential backoff
                      (``backoff_s * 2^k`` capped at ``backoff_max_s``);
- every restart consumes one unit of the ``max_restarts`` budget — a
  flapping run eventually surfaces as a failure instead of burning quota
  forever.

On each launch attempt the child argv is rewritten:

- ``--resume <checkpoint_dir>`` is inserted (or its value replaced)
  whenever the checkpoint dir holds a step — so attempt 0 also resumes
  if a previous supervisor incarnation left a checkpoint behind;
- ``--metrics-jsonl PATH`` becomes ``PATH.attempt<K>`` for K >= 1, so
  every attempt leaves an intact, independently-lintable stream (a
  JsonlSink truncates at open — rewriting would destroy attempt K-1's
  forensics).  A RELAUNCHED supervisor continues the numbering past
  whatever ``PATH``/``PATH.attempt*`` files already exist, so a
  previous incarnation's forensics survive too.

The supervisor keeps its OWN telemetry stream (``metrics_jsonl``):
``run_header`` (platform "supervisor"), a ``resume`` record per
checkpoint-resumed launch, a ``restart`` record per restart decision
(exit code, reason, the v10 exit ``classification`` —
``preempted``/``crashed``/``stall_killed``, the field fleet tooling
keys on — backoff, the child's last step tailed from its metrics
JSONL), and a closing ``run_summary`` carrying ``restart_count`` —
schema v10 (obs/schema.py; hard-coded here to stay import-free).

SIGTERM/SIGINT to the supervisor forward to the child and stop the
restart loop: the child runs its own grace path, the supervisor exits
with the child's status (75 if the child saved — a supervisor-of-
supervisors can resume the whole tree).

Trace continuity (schema v9, obs/trace.py): every child launches with
``APEX_TRACE_ID`` set (inherited from our own environment when a
grand-supervisor set it, else our run id), so the attempt streams of a
``--trace`` child all carry ONE trace_id — a SIGTERM -> drain ->
restart renders as one continuous timeline when
``tools/trace_export.py`` merges them.  When the child argv carries
``--trace`` the supervisor also emits its own side of the story into
its stream: a ``clock_sync`` anchor, an X "attempt" span per child
lifetime and an "i" restart marker per restart decision (timestamps
are ``perf_counter``, like every trace event; the wall clock stays in
the records' ``time`` fields only).

The contract is child-agnostic: serve.py's graceful drain exits the
same 75, so the supervisor restarts a drained server promptly and a
crashed one with backoff.  Serving children differ in two ways —
``resume=False`` skips the ``--resume`` rewrite (serve.py has no resume
concept), and ``drop_flags_on_restart=['--inject-fault']`` strips a
one-shot drill from restart attempts (a served run restarts from tick
0, so the exact-tick fault would otherwise re-fire every attempt).
Metrics rotation and stall-kill work unchanged; a serve stream has no
``step`` records, so ``last_step`` simply stays unreported.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import uuid
from typing import Any, Dict, List, Optional

# Keep in sync with apex_example_tpu/obs/schema.py (SCHEMA_VERSION) and
# resilience/preemption.py (EX_TEMPFAIL) — this module must not import
# either (jax-free contract; same for obs/trace.py's APEX_TRACE_ID).
SCHEMA = 17
EX_TEMPFAIL = 75
TRACE_ID_ENV = "APEX_TRACE_ID"


def latest_checkpoint_step(directory: Optional[str]) -> Optional[int]:
    """Largest orbax step in ``directory`` (step dirs are bare integers),
    without importing orbax: the supervisor only needs to know *whether*
    and *what* to resume — the child does the restoring."""
    if not directory or not os.path.isdir(directory):
        return None
    steps = [int(name) for name in os.listdir(directory)
             if name.isdigit()
             and os.path.isdir(os.path.join(directory, name))]
    return max(steps) if steps else None


_TAIL_BYTES = 256 * 1024


def tail_last_step(path: Optional[str]) -> Optional[int]:
    """Last ``step`` record's step number in a metrics JSONL, or None.
    Reads a bounded tail of the file, not the whole thing — the runs
    the supervisor exists for write one record per optimizer step, and
    a restart decision must not pay a multi-hundred-MB front-to-back
    parse.  Tolerates a torn final line (a killed writer's legitimate
    state) and the torn FIRST line of the tail window."""
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            fh.seek(max(0, size - _TAIL_BYTES))
            chunk = fh.read().decode("utf-8", errors="replace")
    except OSError:  # pragma: no cover
        return None
    for line in reversed(chunk.splitlines()):
        line = line.strip()
        if not line or '"step"' not in line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and rec.get("record") == "step":
            return int(rec.get("step", 0))
    return None


def _set_flag(argv: List[str], flag: str, value: str) -> List[str]:
    """Return argv with ``flag value`` set: replaces an existing
    ``--flag v`` / ``--flag=v`` occurrence, appends otherwise."""
    out: List[str] = []
    i, found = 0, False
    while i < len(argv):
        arg = argv[i]
        if arg == flag and i + 1 < len(argv):
            out.extend([flag, value])
            i, found = i + 2, True
        elif arg.startswith(flag + "="):
            out.append(f"{flag}={value}")
            i, found = i + 1, True
        else:
            out.append(arg)
            i += 1
    if not found:
        out.extend([flag, value])
    return out


def _get_flag(argv: List[str], flag: str) -> Optional[str]:
    for i, arg in enumerate(argv):
        if arg == flag and i + 1 < len(argv):
            return argv[i + 1]
        if arg.startswith(flag + "="):
            return arg.split("=", 1)[1]
    return None


def _strip_flag(argv: List[str], flag: str) -> List[str]:
    """Return argv with every ``flag value`` / ``flag=value`` / bare
    ``flag`` occurrence removed (used by ``drop_flags_on_restart`` —
    e.g. a one-shot ``--inject-fault`` drill that must not re-fire on
    the restarted attempt: a served run restarts from tick 0, so unlike
    a resumed training run the exact-step match would fire again).  The
    following token is only consumed when it is not itself a flag, so
    stripping a store_true flag never swallows an unrelated argument."""
    out: List[str] = []
    i = 0
    while i < len(argv):
        arg = argv[i]
        if arg == flag:
            i += 1
            if i < len(argv) and not argv[i].startswith("-"):
                i += 1                  # the flag's value
        elif arg.startswith(flag + "="):
            i += 1
        else:
            out.append(arg)
            i += 1
    return out


class _Stream:
    """Minimal JSONL writer (the supervisor cannot use obs.JsonlSink —
    jax-free contract).  One file, truncated at first write, flushed per
    record, compact separators like the sink's."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self._fh = None

    def write(self, rec: Dict[str, Any]) -> None:
        if self.path is None:
            return
        if self._fh is None:
            parent = os.path.dirname(self.path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            self._fh = open(self.path, "w")
        self._fh.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class Supervisor:
    """Run a training command as a child process; restart until done.

    ``child_argv`` is the full command (``[python, train.py, ...]``).
    ``checkpoint_dir``/``child_metrics`` default from the child's own
    ``--checkpoint-dir``/``--metrics-jsonl`` flags when present.
    ``sleep_fn`` is injectable for tests.
    """

    def __init__(self, child_argv: List[str],
                 checkpoint_dir: Optional[str] = None,
                 metrics_jsonl: Optional[str] = None,
                 child_metrics: Optional[str] = None,
                 max_restarts: int = 3,
                 backoff_s: float = 1.0,
                 backoff_max_s: float = 60.0,
                 preempt_delay_s: float = 0.0,
                 stall_kill_s: float = 0.0,
                 resume: bool = True,
                 drop_flags_on_restart: Optional[List[str]] = None,
                 sleep_fn=time.sleep,
                 log=print):
        if not child_argv:
            raise ValueError("supervisor needs a child command")
        self.child_argv = list(child_argv)
        # resume=False: never rewrite --resume (children without a resume
        # concept — serve.py restores params via its own flags and would
        # reject an injected --resume).  drop_flags_on_restart: child
        # flags stripped from every restart attempt's argv (one-shot
        # fault drills).
        self.resume = bool(resume)
        self.drop_flags_on_restart = list(drop_flags_on_restart or [])
        self.checkpoint_dir = checkpoint_dir \
            or _get_flag(self.child_argv, "--checkpoint-dir")
        # An EXPLICIT --child-metrics always wins for tailing (the child
        # may be a wrapper whose own --metrics-jsonl is not where the
        # real stream lands); the child's flag is only the default.
        self._explicit_tail = child_metrics
        self.child_metrics = child_metrics \
            or _get_flag(self.child_argv, "--metrics-jsonl")
        self.max_restarts = int(max_restarts)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.preempt_delay_s = float(preempt_delay_s)
        self.stall_kill_s = float(stall_kill_s)
        self.sleep_fn = sleep_fn
        self.log = log
        self.run_id = uuid.uuid4().hex[:12]
        self.restart_count = 0
        self._stream = _Stream(metrics_jsonl)
        # Cross-restart trace continuity: children join OUR trace (or
        # the one a grand-supervisor handed us) via the env; our own
        # trace events are only emitted when the child actually traces.
        self.trace_id = os.environ.get(TRACE_ID_ENV) or self.run_id
        self._tracing = any(a == "--trace" for a in self.child_argv)
        self._trace_synced = False
        self._stop = False
        self._child: Optional[subprocess.Popen] = None
        self._stall_killed = False
        # Rotating --metrics-jsonl per attempt is only legal when the
        # CHILD's own argv carries the flag — and rotation bases on THAT
        # value, never on ``child_metrics`` (which --child-metrics may
        # override to a different, tail-only location).  A path supplied
        # via --child-metrics alone is tail-only (the child may be a
        # wrapper that rejects unknown flags).
        self._child_metrics_flag = _get_flag(self.child_argv,
                                             "--metrics-jsonl")
        self._child_owns_metrics = self._child_metrics_flag is not None
        if self._explicit_tail == self._child_metrics_flag:
            # Same path as the child's own flag: not a wrapper redirect,
            # so tailing must FOLLOW the per-attempt rotation or every
            # restarted child would be watched at a file it no longer
            # writes (--stall-kill would kill healthy children).
            self._explicit_tail = None
        self._attempt_offset = 0            # set by run(): see below

    # --------------------------------------------------------- records

    def _header(self) -> None:
        self._stream.write({
            "record": "run_header", "schema": SCHEMA, "time": time.time(),
            "run_id": self.run_id, "num_devices": 0, "process_index": 0,
            "platform": "supervisor",
            "config": {"checkpoint_dir": self.checkpoint_dir,
                       "child_metrics": self.child_metrics,
                       "max_restarts": self.max_restarts,
                       "backoff_s": self.backoff_s,
                       "backoff_max_s": self.backoff_max_s,
                       "preempt_delay_s": self.preempt_delay_s,
                       "stall_kill_s": self.stall_kill_s},
            "argv": [str(a) for a in self.child_argv]})

    def _summary(self, exit_code: int, last_step: Optional[int]) -> None:
        self._stream.write({
            "record": "run_summary", "time": time.time(),
            "steps": int(last_step or 0), "overflow_count": 0,
            "restart_count": self.restart_count,
            "exit_code": int(exit_code)})

    def _trace_event(self, ph: str, name: str, ts: float,
                     dur: Optional[float] = None,
                     args: Optional[Dict[str, Any]] = None) -> None:
        """Schema-v9 trace_event into the supervisor's own stream
        (hard-coded like every record here — the jax-free contract
        forbids importing obs/trace.py's Tracer, not matching it).
        ``ts``/``dur`` are perf_counter seconds; the lazy clock_sync
        anchors them to the wall clock for the exporter."""
        if not self._tracing:
            return
        if not self._trace_synced:
            self._stream.write({
                "record": "clock_sync", "time": time.time(),
                "ts": time.perf_counter(), "trace_id": self.trace_id,
                "run_id": self.run_id})
            self._trace_synced = True
        rec: Dict[str, Any] = {
            "record": "trace_event", "ph": ph, "name": name, "ts": ts,
            "tid": "supervisor", "trace_id": self.trace_id,
            "run_id": self.run_id}
        if dur is not None:
            rec["dur"] = dur
        if args:
            rec["args"] = args
        self._stream.write(rec)

    # ----------------------------------------------------------- child

    def _existing_attempt_offset(self) -> int:
        """First attempt index whose stream file does not exist yet.  A
        RELAUNCHED supervisor (host reboot, operator re-run) must not
        let its attempt-0 child truncate a previous incarnation's
        forensics — the JsonlSink truncates at open, so numbering
        continues past whatever is already on disk."""
        if not self._child_owns_metrics:
            return 0
        base = self._child_metrics_flag
        # Scan the directory, not a contiguous probe: a predecessor may
        # have left .attempt2 without base or .attempt1 (its own offset,
        # or a child that died before opening its stream).
        found = [0] if os.path.exists(base) else []
        parent = os.path.dirname(base) or "."
        prefix = os.path.basename(base) + ".attempt"
        try:
            names = os.listdir(parent)
        except OSError:  # pragma: no cover
            names = []
        for name in names:
            if name.startswith(prefix) and name[len(prefix):].isdigit():
                found.append(int(name[len(prefix):]))
        return max(found) + 1 if found else 0

    def _flag_path(self, attempt: int) -> str:
        """Where attempt K's child writes (its own --metrics-jsonl,
        rotated past both earlier attempts AND earlier incarnations)."""
        n = attempt + self._attempt_offset
        return self._child_metrics_flag if n == 0 \
            else f"{self._child_metrics_flag}.attempt{n}"

    def _metrics_path(self, attempt: int) -> Optional[str]:
        """Where attempt K's stream is TAILED from: an explicit
        --child-metrics always wins; otherwise the child's own rotated
        flag path; None when neither names a file."""
        if self._explicit_tail:
            return self._explicit_tail
        if not self._child_owns_metrics:
            return None
        return self._flag_path(attempt)

    def _launch_argv(self, attempt: int) -> List[str]:
        argv = list(self.child_argv)
        ckstep = latest_checkpoint_step(self.checkpoint_dir)
        # Records and logs carry the incarnation-GLOBAL attempt index so
        # they match the .attempt<N> stream filenames after a supervisor
        # relaunch (offset > 0).
        n = attempt + self._attempt_offset
        if n > 0:
            for flag in self.drop_flags_on_restart:
                argv = _strip_flag(argv, flag)
        if not self.resume:
            ckstep = None
        if ckstep is not None:
            argv = _set_flag(argv, "--resume", self.checkpoint_dir)
            self._stream.write({
                "record": "resume", "time": time.time(),
                "run_id": self.run_id, "attempt": n,
                "checkpoint_step": ckstep,
                "resume_dir": self.checkpoint_dir})
            self.log(f"supervisor: attempt {n} resumes from "
                     f"{self.checkpoint_dir} (step {ckstep})")
        if self._child_owns_metrics and attempt + self._attempt_offset > 0:
            argv = _set_flag(argv, "--metrics-jsonl",
                             self._flag_path(attempt))
        return argv

    def _wait(self, metrics_path: Optional[str]) -> int:
        """Wait for the child; with ``stall_kill_s`` > 0 AND a child
        metrics path to watch, SIGKILL a child whose stream stops
        advancing (the 'hang' fault's backstop — a wedged device never
        exits on its own).  Without a metrics path there is nothing to
        measure progress by, so stall-kill stays disarmed rather than
        killing every child that merely outlives the deadline."""
        child = self._child
        t_start = time.time()
        watch = self.stall_kill_s > 0 and metrics_path is not None
        if not watch:
            # Nothing to measure progress by: block in wait() instead of
            # polling for hours.  Signal forwarding still works — the
            # handler signals the child, whose exit unblocks the wait.
            return child.wait()
        while True:
            rc = child.poll()
            if rc is not None:
                return rc
            # File not created yet counts from launch: a child that
            # never opens its stream within the deadline is as wedged
            # as one that stopped writing to it.
            last = t_start
            if os.path.exists(metrics_path):
                try:
                    last = max(last, os.path.getmtime(metrics_path))
                except OSError:  # pragma: no cover
                    pass
            if time.time() - last > self.stall_kill_s:
                self.log(f"supervisor: no progress for "
                         f"{self.stall_kill_s:.0f}s, killing child")
                # Provenance for the restart record: reason 'stall'
                # means WE killed it — an external SIGKILL (OOM killer,
                # operator) is a plain crash.
                self._stall_killed = True
                child.kill()
                child.wait()
                return child.returncode
            time.sleep(0.2)

    # ------------------------------------------------------------- run

    def _forward_signal(self, signum, frame) -> None:
        self._stop = True
        if self._child is not None and self._child.poll() is None:
            try:
                self._child.send_signal(signum)
            except OSError:  # pragma: no cover
                pass

    def run(self) -> int:
        self._header()
        self._attempt_offset = self._existing_attempt_offset()
        if self._attempt_offset:
            self.log(f"supervisor: streams from a previous incarnation "
                     f"found; new attempts write from "
                     f".attempt{self._attempt_offset}")
        prev_handlers = {}
        if hasattr(signal, "SIGTERM"):
            import threading
            if threading.current_thread() is threading.main_thread():
                for sig in (signal.SIGTERM, signal.SIGINT):
                    try:
                        prev_handlers[sig] = signal.signal(
                            sig, self._forward_signal)
                    except (ValueError, OSError):  # pragma: no cover
                        pass
        attempt = 0
        crash_restarts = 0
        rc = 1
        last_step_seen: Optional[int] = None
        try:
            while True:
                if self._stop:
                    # A stop signal that arrived with no child alive
                    # (during the backoff sleep, or between launches)
                    # must not spawn another attempt.
                    self.log("supervisor: stopping (signal received), "
                             "no further restarts")
                    return rc
                argv = self._launch_argv(attempt)
                metrics_path = self._metrics_path(attempt)
                self._stall_killed = False
                t_launch = time.time()
                t_launch_perf = time.perf_counter()
                # Children join the supervisor's trace: a --trace
                # child's Tracer picks the id up from the env, so a
                # drain -> restart renders as ONE timeline across the
                # attempt streams (obs/trace.py).
                child_env = dict(os.environ)
                child_env[TRACE_ID_ENV] = self.trace_id
                self._child = subprocess.Popen(argv, env=child_env)
                if self._stop:
                    # A stop signal that raced the launch (after the
                    # loop-top check, before Popen) was forwarded to a
                    # child that no longer existed; deliver it to this
                    # one so its grace path still runs.
                    try:
                        self._child.send_signal(signal.SIGTERM)
                    except OSError:  # pragma: no cover
                        pass
                rc = self._wait(metrics_path)
                self._trace_event(
                    "X", "attempt", t_launch_perf,
                    dur=time.perf_counter() - t_launch_perf,
                    args={"attempt": attempt + self._attempt_offset,
                          "exit_code": int(rc)})
                # Only trust a tail the CHILD just wrote: a file whose
                # mtime predates this launch is a previous attempt's (or
                # a previous supervisor incarnation's) — a child that
                # died before opening its stream made no progress.
                last_step = None
                if metrics_path and os.path.exists(metrics_path):
                    try:
                        fresh = os.path.getmtime(metrics_path) \
                            >= t_launch - 1.0
                    except OSError:  # pragma: no cover
                        fresh = False
                    if fresh:
                        last_step = tail_last_step(metrics_path)
                if last_step is not None:
                    last_step_seen = last_step
                ckstep = latest_checkpoint_step(self.checkpoint_dir)
                if rc == 0:
                    self.log(f"supervisor: child done after "
                             f"{self.restart_count} restart(s)")
                    return 0
                if self._stop:
                    self.log(f"supervisor: stopping (forwarded signal), "
                             f"child exited {rc}")
                    return rc
                if self.restart_count >= self.max_restarts:
                    self.log(f"supervisor: restart budget "
                             f"({self.max_restarts}) exhausted, child "
                             f"exited {rc}")
                    return rc
                if rc == EX_TEMPFAIL:
                    reason, backoff = "preemption", self.preempt_delay_s
                    classification = "preempted"
                else:
                    reason = "stall" if self._stall_killed else "crash"
                    classification = "stall_killed" if self._stall_killed \
                        else "crashed"
                    backoff = min(self.backoff_s * (2 ** crash_restarts),
                                  self.backoff_max_s)
                    crash_restarts += 1
                # v10: the exit classification rides the restart record
                # so fleet tooling (fleet/replica.py's health tail,
                # tools/fleet_report.py) can tell a drain from a crash
                # without re-parsing the child's own stream.
                rec: Dict[str, Any] = {
                    "record": "restart", "time": time.time(),
                    "run_id": self.run_id,
                    "attempt": attempt + self._attempt_offset,
                    "exit_code": int(rc), "reason": reason,
                    "classification": classification,
                    "backoff_s": float(backoff)}
                if last_step is not None:
                    rec["last_step"] = last_step
                if ckstep is not None:
                    rec["checkpoint_step"] = ckstep
                self._stream.write(rec)
                self._trace_event(
                    "i", "restart", time.perf_counter(),
                    args={"attempt": attempt + self._attempt_offset,
                          "exit_code": int(rc), "reason": reason,
                          "backoff_s": float(backoff)})
                self.log(f"supervisor: child exited {rc} ({reason}) at "
                         f"step {last_step if last_step is not None else '?'}"
                         f", checkpoint at "
                         f"{ckstep if ckstep is not None else 'none'}; "
                         f"restarting in {backoff:.1f}s "
                         f"({self.restart_count + 1}/{self.max_restarts})")
                if backoff > 0:
                    self.sleep_fn(backoff)
                self.restart_count += 1
                attempt += 1
        finally:
            # The last step any attempt ACTUALLY reached (freshness-
            # gated above) — never a stale file's count, and never an
            # earlier attempt's by accident (a stop during backoff has
            # already advanced `attempt` past the last launch).
            self._summary(rc, last_step_seen)
            self._stream.close()
            for sig, prev in prev_handlers.items():
                try:
                    signal.signal(sig, prev)
                except (ValueError, OSError):  # pragma: no cover
                    pass


def main(argv: Optional[List[str]] = None) -> int:  # pragma: no cover
    """Module-level entry so ``python -m`` style invocation works when
    loaded by path; the real CLI (argparse surface) is tools/supervise.py.
    """
    sys.stderr.write("use tools/supervise.py\n")
    return 2
