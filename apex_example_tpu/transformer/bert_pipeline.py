"""Pipeline-parallel BERT/GPT training step (train.py --pipeline-parallel).

Reference: apex.transformer's pipeline_parallel package drives Megatron-LM
models through its schedules; the in-tree schedules here
(pipeline_parallel/schedules.py) were previously exercised on synthetic
stage functions only.  This module closes the integration gap for real
workloads: BERT-for-MLM and GPT causal LM (one schedule body serves both —
the GPT (x, y) batch becomes the MLM shape with all-ones weights, a
causal layer stack, and its own head cell), stages = contiguous blocks of
encoder layers, driven through the SPMD ring schedule over a
('pipe', 'data') mesh.

Design (TPU-native, *uniform-schedule* form):

- The encoder layers — where the FLOPs and params live — are stacked into
  one [num_layers, ...] pytree and sharded P('pipe') on the stacked dim:
  each stage owns num_layers/S contiguous layers and scans over them.
- Embedding and MLM head are REPLICATED-COMPUTE: every stage evaluates
  them, but only stage 0 consumes the embedded activations (the ring
  schedule's injection mask) and only the last stage consumes the head
  (the loss mask), so the masked cotangents + the automatic psum of
  invariant-param grads yield exactly the right gradients — including the
  tied decoder, whose table grad is the psum of the stage-0 embedding
  contribution and the last-stage decode contribution.  This trades a
  little redundant forward compute for a schedule with NO special-cased
  first/last stage (Megatron instead places the embedding on stage 0 and
  shares it with the last stage via a dedicated all-reduce).
- Data parallelism composes on the 'data' mesh axis: the global batch
  shards over it, per-shard microbatches feed the ring, grads of
  replicated params psum over both axes automatically.
- Tensor parallelism composes on the 'model' mesh axis (reference:
  apex.transformer.parallel_state exists precisely to run TP+PP+DP
  jointly, SURVEY.md:149-151).  TPU-native form: the shard_map is manual
  over ('pipe', 'data') ONLY (``axis_names``), leaving 'model' an
  *automatic* axis inside the body — so the stage function runs the same
  GSPMD TP layers (column/row-parallel, ``tensor_parallel=True``) as the
  pure-TP path, with their sharding constraints binding to the still-auto
  model axis and GSPMD inserting the Megatron collectives inside each
  ring tick.  Stacked layer params shard over BOTH axes: P('pipe') on the
  stacked dim via in_specs, column/row metadata over 'model' riding along
  as the arrays' auto-axis sharding.  Embedding and MLM head stay
  replicated-compute over 'model' (their FLOPs are a rounding error at
  BERT scale; the encoder is where TP pays).

The param tree is IDENTICAL in content to the dense
``models.bert.BertForMaskedLM`` tree (``pack_params``/``unpack_params``
convert), so checkpoints interchange and tests compare trajectories
against the single-device model directly.

Dynamic loss scaling (fp16 O1/O2) composes with the schedule without any
per-microbatch plumbing: an overflow anywhere in the schedule poisons the
ACCUMULATED grads (inf/nan propagates through the scan and the psums), so
the post-schedule finite check sees it; rest-param grads are psum'd over
pipe+data (making their flag mesh-invariant already) and the stage-local
layer-grad flags are pmean'd over 'pipe', so every stage takes the same
all-or-none skip — the same protocol the TP and ZeRO paths use.  This goes
beyond the reference, whose pipeline schedules do not compose with apex
AMP's dynamic scaler (Megatron uses its own grad scaler).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from apex_example_tpu import amp as amp_lib
from apex_example_tpu.amp.policy import Policy
from apex_example_tpu.engine import TrainState, _wrap_optimizer
from apex_example_tpu.models.bert import BertForMaskedLM, BertLayer
from apex_example_tpu.ops.layer_norm import layer_norm
from apex_example_tpu.ops.xentropy import softmax_cross_entropy
from apex_example_tpu.parallel.mesh import DATA_AXIS, PIPE_AXIS
from apex_example_tpu.transformer.pipeline_parallel.schedules import (
    pipeline_1f1b, spmd_pipeline)

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

def _rest_keys(dense_params) -> Tuple[str, ...]:
    """Everything that is not a stacked encoder layer — embedding + head
    params.  Derived from the tree itself so one pack/unpack pair serves
    both BertForMaskedLM (mlm_dense/mlm_ln/mlm_bias) and GPTForCausalLM
    (final_ln/lm_bias)."""
    return tuple(k for k in dense_params if not k.startswith("layer_"))


def pack_params(dense_params: Dict[str, Any], num_layers: int
                ) -> Dict[str, Any]:
    """Dense BertForMaskedLM/GPTForCausalLM tree ->
    {'rest': ..., 'layers': stacked}."""
    layers = [dense_params[f"layer_{i}"] for i in range(num_layers)]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)
    return {"rest": {k: dense_params[k] for k in _rest_keys(dense_params)},
            "layers": stacked}


def unpack_params(packed: Dict[str, Any], num_layers: int) -> Dict[str, Any]:
    out = dict(packed["rest"])
    for i in range(num_layers):
        out[f"layer_{i}"] = jax.tree_util.tree_map(
            lambda x: x[i], packed["layers"])
    return out


def _1f1b_order(num_layers: int, stages: int, num_chunks: int):
    """Dense-layer index for each (stage, chunk, slot): global stage
    v·S+s owns the contiguous dense block [(v·S+s)·per, +per) — the
    interleaved-virtual-stage assignment (device s holds chunks {v·S+s})."""
    if num_layers % (stages * num_chunks):
        raise ValueError(
            f"num_layers {num_layers} not divisible by stages {stages} x "
            f"chunks {num_chunks} — layers would be silently dropped")
    per = num_layers // (stages * num_chunks)
    return [[(v * stages + s) * per + i
             for v in range(num_chunks) for i in range(per)]
            for s in range(stages)], per


def pack_params_1f1b(dense_params: Dict[str, Any], num_layers: int,
                     stages: int, num_chunks: int = 1) -> Dict[str, Any]:
    """Dense tree -> {'rest', 'layers'} ARRANGED for the 1F1B schedules:
    layer leaves are [S, V, per, ...] with [s, v, i] holding dense layer
    (v·S+s)·per + i, so a P('pipe') shard hands device s exactly its
    chunks.  (The ring pack's contiguous [num_layers, ...] stack cannot
    express the interleaved assignment — chunk v·S+s for v>0 is not a
    contiguous slice of device s's shard.)"""
    order, per = _1f1b_order(num_layers, stages, num_chunks)
    rows = [jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs).reshape(num_chunks, per, *xs[0].shape),
        *[dense_params[f"layer_{j}"] for j in row]) for row in order]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *rows)
    return {"rest": {k: dense_params[k] for k in _rest_keys(dense_params)},
            "layers": stacked}


def unpack_params_1f1b(packed: Dict[str, Any], num_layers: int,
                       stages: int, num_chunks: int = 1) -> Dict[str, Any]:
    out = dict(packed["rest"])
    order, per = _1f1b_order(num_layers, stages, num_chunks)
    for s, row in enumerate(order):
        for slot, j in enumerate(row):
            v, i = divmod(slot, per)
            out[f"layer_{j}"] = jax.tree_util.tree_map(
                lambda x, s=s, v=v, i=i: x[s, v, i], packed["layers"])
    return out


def _embed(rest, ids, model):
    """Embedding + post-embedding LN, matching BertForMaskedLM.__call__
    (GPTForCausalLM uses the identical names and math).

    Under CP x PP (``model.context_parallel``) ``ids`` is this shard's
    contiguous sequence chunk: positions offset by the context-shard
    index, exactly like the models' own CP branch (contiguous/ring
    layout; the zigzag layout is rejected at the factory)."""
    dtype = model.dtype
    ln_io = model.ln_dtype or dtype
    L = ids.shape[-1]
    x = jnp.take(rest["word_embeddings"]["embedding"], ids,
                 axis=0).astype(dtype)
    pos_tbl = rest["position_embeddings"]["embedding"]
    if getattr(model, "context_parallel", False):
        from apex_example_tpu.parallel.mesh import CONTEXT_AXIS
        i = lax.axis_index(CONTEXT_AXIS)
        if getattr(model, "cp_mode", "ring") == "zigzag":
            # zigzag layout: this shard's halves are global chunks i and
            # 2n-1-i (the models' own CP branch algebra; the factory's
            # zigzag_shard pre-pass reordered the tokens to match)
            n = lax.axis_size(CONTEXT_AXIS)
            c = L // 2
            pos = jnp.concatenate([jnp.arange(c) + i * c,
                                   jnp.arange(c) + (2 * n - 1 - i) * c])
        else:
            pos = jnp.arange(L) + i * L
        x = x + jnp.take(pos_tbl, pos, axis=0)[None].astype(dtype)
    else:
        x = x + pos_tbl[:L][None].astype(dtype)
    x = layer_norm(x.astype(ln_io), rest["embeddings_ln"]["scale"],
                   rest["embeddings_ln"]["bias"])
    return x.astype(dtype)


def _head_loss_sum(rest, y, labels, weights, model: BertForMaskedLM):
    """MLM head (dense+gelu+LN, tied decoder) + weighted CE *sum*, matching
    BertForMaskedLM.__call__.  Returns the un-normalized Σ ce·w: the global
    masked-position denominator is applied outside the pipeline so the loss
    equals workloads.mlm_loss on the full batch exactly (a per-microbatch
    mean-of-means would weight microbatches with different masked counts
    unequally)."""
    dtype = model.dtype
    ln_io = model.ln_dtype or dtype
    x = y.astype(dtype) @ rest["mlm_dense"]["kernel"].astype(dtype) \
        + rest["mlm_dense"]["bias"].astype(dtype)
    x = jax.nn.gelu(x, approximate=False)
    x = layer_norm(x.astype(ln_io), rest["mlm_ln"]["scale"],
                   rest["mlm_ln"]["bias"]).astype(dtype)
    logits = x @ rest["word_embeddings"]["embedding"].astype(dtype).T
    logits = logits.astype(jnp.float32) + rest["mlm_bias"]
    ce = softmax_cross_entropy(logits, labels)
    return (ce * weights).sum()


def _gpt_head_loss_sum(rest, y, labels, weights, model):
    """GPT head (final LN + tied decoder) + CE *sum*, matching
    GPTForCausalLM.__call__.  ``weights`` is all-ones from the factory, so
    the shared global denominator turns the sum into exactly
    workloads.lm_loss's mean over the full batch."""
    dtype = model.dtype
    ln_io = model.ln_dtype or dtype
    x = layer_norm(y.astype(ln_io), rest["final_ln"]["scale"],
                   rest["final_ln"]["bias"]).astype(dtype)
    logits = x @ rest["word_embeddings"]["embedding"].astype(dtype).T
    logits = logits.astype(jnp.float32) + rest["lm_bias"]
    ce = softmax_cross_entropy(logits, labels)
    return (ce * weights).sum()


def _tp_layer_specs(model):
    """Per-leaf PartitionSpecs of ONE encoder layer under TP (the flax
    with_partitioning metadata of the column/row-parallel layers), shaped
    like an entry of the packed ``layers`` subtree minus the stacked dim."""
    import flax.linen as nn
    layer_mod = BertLayer(model.hidden_size, model.num_heads,
                          model.intermediate_size, model.dtype,
                          model.param_dtype, model.ln_dtype,
                          model.softmax_dtype,
                          fused_attention=model.fused_attention,
                          tensor_parallel=True,
                          sequence_parallel=model.sequence_parallel)
    abs_x = jax.ShapeDtypeStruct((1, 8, model.hidden_size), model.dtype)
    abs_vars = jax.eval_shape(
        lambda r, x: layer_mod.init(r, x, None),
        jax.random.PRNGKey(0), abs_x)
    return nn.get_partition_spec(abs_vars)["params"]


def _moe_pp_layers_spec(layers_tree):
    """Per-leaf specs for an EP x PP packed ``layers`` subtree: expert
    stacks (workloads._is_expert_leaf) shard [stacked->pipe,
    experts->data], everything else P('pipe') on the stacked dim only.
    ONE definition shared by the in_specs and the placement shardings."""
    from apex_example_tpu.workloads import _is_expert_leaf
    return jax.tree_util.tree_map_with_path(
        lambda path, _leaf: P(PIPE_AXIS, DATA_AXIS)
        if _is_expert_leaf(path) else P(PIPE_AXIS), layers_tree)


def _is_moe_ep(model) -> bool:
    return bool(getattr(model, "moe_experts", 0)) and \
        getattr(model, "moe_axis_name", "") == DATA_AXIS


def bert_pp_state_shardings(mesh: Mesh, state: TrainState, optimizer,
                            model: Optional[BertForMaskedLM] = None
                            ) -> TrainState:
    """NamedSharding pytree for a packed-params TrainState: layers shard
    their stacked dim over 'pipe', everything else replicates, optimizer
    state mirrors its params-shaped fields.  Used both to place the initial
    state and as the orbax restore template (cf.
    utils.checkpoint.restore_under_mesh for the DP/ZeRO/CP paths).

    With a ``tensor_parallel`` model, layer leaves additionally shard over
    'model' per the TP layers' column/row partitioning metadata —
    P('pipe', …, 'model', …) — the jointly-sharded placement of the TP×PP
    composition (rest/embedding/head still replicate)."""
    from apex_example_tpu.engine import _opt_state_specs
    tmap = jax.tree_util.tree_map
    if model is not None and model.tensor_parallel:
        # Pad between the 'pipe'-sharded stacked dim and the layer's own
        # TP spec: the ring pack has ONE leading index dim ([L, ...]), the
        # 1F1B arranged pack has THREE ([S, V, per, ...]) — the TP axes
        # always name the trailing (per-layer) dims.
        layer_specs = tmap(
            lambda s, leaf: P(PIPE_AXIS,
                              *([None] * (leaf.ndim - 1 - len(tuple(s)))),
                              *tuple(s)),
            _tp_layer_specs(model), state.params["layers"],
            is_leaf=lambda v: isinstance(v, P))
    elif model is not None and _is_moe_ep(model):
        layer_specs = _moe_pp_layers_spec(state.params["layers"])
    else:
        layer_specs = tmap(lambda _: P(PIPE_AXIS), state.params["layers"])
    params_specs = {
        "rest": tmap(lambda _: P(), state.params["rest"]),
        "layers": layer_specs,
    }
    abs_params = tmap(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                      state.params)
    # PipelineZeroAdam's flat [S, padded] buffers do not mirror the params
    # tree — they carry their own spec (P(pipe, data)).
    opt_specs = optimizer.state_spec() \
        if isinstance(optimizer, PipelineZeroAdam) \
        else _opt_state_specs(optimizer, abs_params, params_specs)
    spec_state = TrainState(
        step=P(), params=params_specs,
        batch_stats=tmap(lambda _: P(), state.batch_stats),
        opt_state=opt_specs,
        scaler=tmap(lambda _: P(), state.scaler))
    from jax.sharding import NamedSharding
    return tmap(lambda s: NamedSharding(mesh, s), spec_state,
                is_leaf=lambda v: isinstance(v, P))


class PipelineZeroState(NamedTuple):
    """ZeRO x PP optimizer state: one flat fp32 buffer pair for the
    (pipe-invariant, replicated-compute) embedding/head params, sharded
    P('data'), and one per-stage pair for the layer blocks, sharded
    P('pipe', 'data')."""
    step: jnp.ndarray
    rest_mu: jnp.ndarray
    rest_nu: jnp.ndarray
    layer_mu: jnp.ndarray
    layer_nu: jnp.ndarray


class PipelineZeroAdam:
    """ZeRO-1 Adam for the packed ``{'rest', 'layers'}`` pipeline tree —
    the ZeRO x PP pairing (the reference's distributed_fused_adam is run
    with Megatron PP in practice; DeepSpeed's "3D" stacks the same way).

    Each pipe stage flattens ITS local packed slice (rest + its layer
    block) into one fp32 buffer whose (m, v) shard over 'data' via the
    inner :class:`DistributedFusedAdam` — per-device optimizer state is
    1/data-axis of the STAGE-local params.  (The 'rest' state is
    per-stage duplicated, mirroring the schedule's replicated-compute
    embedding/head: still 1/dp of the non-ZeRO form per device.)

    ``init`` runs OUTSIDE the mesh on the global packed tree and returns
    ``[S, padded_local]`` buffers; ``state_spec`` shards them
    P('pipe', 'data'); ``apply`` runs INSIDE the shard_map on the local
    slice (the inner optimizer sees exactly its per-stage tree, whose
    flat size matches init's arithmetic because every ``layers`` leaf
    splits its stacked dim 0 S-ways).  The inner optimizer must carry
    ``grads_global_mean=True``: the PP losses are psum-normalized
    globally, so grads arrive as the true global mean (see
    optim/distributed.py).
    """

    def __init__(self, zadam, stages: int):
        from apex_example_tpu.optim.distributed import DistributedFusedAdam
        if not isinstance(zadam, DistributedFusedAdam):
            raise TypeError(f"PipelineZeroAdam wraps DistributedFusedAdam, "
                            f"got {type(zadam).__name__}")
        if not zadam.grads_global_mean:
            raise ValueError(
                "PipelineZeroAdam needs DistributedFusedAdam("
                "grads_global_mean=True): the PP losses are globally "
                "psum-normalized, so dividing by world again would hand "
                "Adam g/world")
        self.z = zadam
        self.stages = stages

    def _padded_sizes(self, packed):
        from apex_example_tpu.optim.distributed import (_flat_size,
                                                        _padded_size)
        S = self.stages
        rest = _padded_size(_flat_size(packed["rest"]), self.z.world)
        layers = _padded_size(
            sum(int(l.size) // S
                for l in jax.tree_util.tree_leaves(packed["layers"])),
            self.z.world)
        return rest, layers

    def init(self, packed):
        if not (isinstance(packed, dict) and "rest" in packed):
            # The harness bootstraps a dense state first (its opt state is
            # discarded and rebuilt from the packed tree) — mirror
            # PipelineFusedLAMB's any-tree tolerance with a throwaway
            # inner-form state.
            return self.z.init(packed)
        pr, pl = self._padded_sizes(packed)
        return PipelineZeroState(
            step=jnp.zeros((), jnp.int32),
            rest_mu=jnp.zeros((pr,), jnp.float32),
            rest_nu=jnp.zeros((pr,), jnp.float32),
            layer_mu=jnp.zeros((self.stages, pl), jnp.float32),
            layer_nu=jnp.zeros((self.stages, pl), jnp.float32))

    def state_spec(self):
        d = self.z.axis_name
        return PipelineZeroState(step=P(), rest_mu=P(d), rest_nu=P(d),
                                 layer_mu=P(PIPE_AXIS, d),
                                 layer_nu=P(PIPE_AXIS, d))

    def apply(self, grads, state, params):
        from apex_example_tpu.optim.distributed import ZeroAdamState
        # Two independent flat buffers so the vma typing stays exact:
        # 'rest' (pipe-INVARIANT inputs -> invariant outputs, no extra
        # collective) and this stage's layer block (pipe-varying, the
        # [S, padded] buffers arrive as this (stage, data) cell's
        # [1, padded/dp] slice; the inner contract is the bare local
        # shard of a P(data) buffer).
        new_rest, st_r = self.z.apply(
            grads["rest"],
            ZeroAdamState(step=state.step, mu=state.rest_mu,
                          nu=state.rest_nu),
            params["rest"])
        new_layers, st_l = self.z.apply(
            grads["layers"],
            ZeroAdamState(step=state.step, mu=state.layer_mu[0],
                          nu=state.layer_nu[0]),
            params["layers"])
        # One step counter: both inner applies take the same skip decision
        # whenever the engine's global finite flag lets the update stand
        # (a partially-finite step is rolled back wholesale by the
        # engine's select_tree), so st_r.step is THE step.
        return ({"rest": new_rest, "layers": new_layers},
                PipelineZeroState(step=st_r.step, rest_mu=st_r.mu,
                                  rest_nu=st_r.nu,
                                  layer_mu=st_l.mu[None],
                                  layer_nu=st_l.nu[None]))


class PipelineFusedLAMB:
    """FusedLAMB for the packed ``{'rest', 'layers'}`` pipeline tree.

    Plain FusedLAMB on the packed tree would be silently wrong twice over
    (which is why :func:`make_bert_pp_train_step` rejects it): a stacked
    ``[num_layers, …]`` leaf would get ONE cross-layer trust ratio where
    the dense model computes one per layer's tensor, and the global
    gradient-norm clip would see only THIS stage's layer grads.  This
    wrapper restores the dense semantics exactly:

    - stacked leaves run LAMB stage 1/2 per layer slice (a static unrolled
      loop over the stage's ``per_stage`` layers — the same per-leaf fused
      kernels the dense path runs, so trust ratios match it bitwise);
    - the clip norm is assembled globally: Σ‖g‖² of the (pipe-invariant)
      rest leaves plus a psum over 'pipe' of the stage-local layer Σ‖g‖².

    ``apply`` must run inside shard_map with ``axis_name`` bound (the PP
    per-shard step); ``init`` works on any tree and simply mirrors it.
    Under TP×PP the model axis stays automatic, so the per-layer norms are
    full logical reductions — GSPMD inserts the model-axis psums.
    """

    def __init__(self, lamb, axis_name: str = PIPE_AXIS,
                 stacked_dims: int = 1):
        from apex_example_tpu.optim.fused import FusedLAMB
        if not isinstance(lamb, FusedLAMB):
            raise TypeError(f"PipelineFusedLAMB wraps FusedLAMB, got "
                            f"{type(lamb).__name__}")
        self.lamb = lamb
        self.axis_name = axis_name
        # Leading per-layer index dims on each stacked leaf: 1 for the ring
        # pack ([num_layers, ...]), 3 for the 1F1B arranged pack
        # ([S, V, per, ...]) — every one of them must be unrolled or a
        # whole [V, per] block would share one trust ratio.
        self.stacked_dims = stacked_dims

    def init(self, params):
        return self.lamb.init(params)

    def apply(self, grads, state, params):
        from apex_example_tpu.ops.multi_tensor import sqsum_leaf
        from apex_example_tpu.optim.fused import (LambState, lamb_clip_scale,
                                                  lamb_step_scalars,
                                                  lamb_update_leaf)
        L = self.lamb
        step = state.step + 1
        c1, c2, lr = lamb_step_scalars(L, step)

        tleaves = jax.tree_util.tree_leaves
        if L.max_grad_norm and L.max_grad_norm > 0:
            rest_sq = sum(sqsum_leaf(g) for g in tleaves(grads["rest"]))
            layer_sq = sum(sqsum_leaf(g) for g in tleaves(grads["layers"]))
            # psum → pipe-invariant, so the shared clip scale (and with it
            # every rest-leaf update) stays invariant too.
            gscale = lamb_clip_scale(
                L, jnp.sqrt(rest_sq + lax.psum(layer_sq, self.axis_name)))
        else:
            gscale = jnp.asarray(1.0, jnp.float32)

        def one(p, g, m, v):
            return lamb_update_leaf(L, p, g, m, v, c1, c2, lr, gscale)

        def stacked(p, g, m, v):
            lead = p.shape[:self.stacked_dims]
            n = 1
            for s in lead:
                n *= s
            rs = lambda t: t.reshape((n,) + p.shape[self.stacked_dims:])
            pf, gf, mf, vf = rs(p), rs(g), rs(m), rs(v)
            outs = [one(pf[l], gf[l], mf[l], vf[l]) for l in range(n)]
            return tuple(
                jnp.stack([o[i] for o in outs]).reshape(p.shape)
                for i in range(3))

        def sweep(fn, sub):
            flat_p, treedef = jax.tree_util.tree_flatten(params[sub])
            flat = [treedef.flatten_up_to(t[sub])
                    for t in (grads, state.mu, state.nu)]
            outs = [fn(p, g, m, v) for p, g, m, v in zip(flat_p, *flat)]
            return tuple(treedef.unflatten([o[i] for o in outs])
                         for i in range(3))

        rp, rm, rv = sweep(one, "rest")
        sp, sm, sv = sweep(stacked, "layers")
        return ({"rest": rp, "layers": sp},
                LambState(step, {"rest": rm, "layers": sm},
                          {"rest": rv, "layers": sv}))


def make_bert_pp_train_step(mesh: Mesh, model: BertForMaskedLM, optimizer,
                            policy: Policy, microbatches: int,
                            donate: bool = True, schedule: str = "ring",
                            num_chunks: int = 1,
                            moe_aux_weight: float = 1e-2):
    """Jitted (state, (ids, (labels, weights))) -> (state, metrics) over a
    ('pipe', 'data') mesh.  ``state.params`` is the packed tree with
    ``layers`` leaves carrying a leading stacked-stage dim (shard
    P('pipe')); batch shards over 'data' and is split into ``microbatches``
    ring slots per shard.

    ``schedule`` picks the pipeline program (all three trajectory-match
    the dense model; reference: the three apex schedule entry points):

    - "ring" (default): the SPMD ring (:func:`schedules.spmd_pipeline`),
      backward derived by autodiff.  State layout: ``pack_params``'s
      [num_layers, ...] stack.  The only schedule that composes with
      tensor parallelism.
    - "1f1b": TRUE 1F1B (:func:`schedules.pipeline_1f1b`) — bounded
      in-flight activations independent of the microbatch count.
      Embedding runs batched OUTSIDE the schedule (its backward completes
      through the returned input cotangents); the parametrized head rides
      the loss cell via ``head_params``.  State layout:
      ``pack_params_1f1b``'s arranged [S, V, per, ...] stack.
    - "interleaved": 1F1B with ``num_chunks`` virtual stages per device
      (the reference's interleaved variant; needs microbatches % S == 0
      and num_layers % (S·num_chunks) == 0).
    """
    S = mesh.shape[PIPE_AXIS]
    if schedule not in ("ring", "1f1b", "interleaved"):
        raise ValueError(f"unknown schedule {schedule!r}")
    if schedule == "interleaved":
        if num_chunks < 2:
            raise ValueError("interleaved schedule needs num_chunks >= 2")
    elif num_chunks != 1:
        # Reject rather than ignore (the same contract train.py states for
        # --virtual-stages): a caller asking for virtual stages on a
        # non-interleaved schedule would otherwise silently get none.
        raise ValueError(f"num_chunks={num_chunks} only applies to the "
                         f"interleaved schedule, not {schedule!r}")
    V = num_chunks if schedule == "interleaved" else 1
    if model.num_layers % (S * V):
        raise ValueError(f"num_layers {model.num_layers} not divisible by "
                         f"pipeline size {S} x chunks {V}")
    from apex_example_tpu.parallel.mesh import (CONTEXT_AXIS,
                                                require_model_axis_match)
    tp = require_model_axis_match(mesh, model.tensor_parallel)
    # TP composes with ALL THREE schedules (round 5; r4 allowed ring
    # only).  NOT via the plain cond dispatch: TP collectives inside the
    # per-stage lax.cond COMPILE fine but DEADLOCK at runtime — devices
    # disagree on the global cross-program collective order (PERF.md
    # round-5 note).  The 1F1B/interleaved cells therefore require the
    # branch-free uniform_collectives form, passed below; any new TP call
    # site of pipeline_1f1b must pass it too.
    # CP x PP (round 5): the sequence additionally shards over 'context'
    # as another manual axis — the KV ring runs INSIDE each stage cell,
    # positions offset in _embed, losses psum over (data, context).  The
    # same uniform-collectives requirement applies on 1F1B/interleaved
    # (the KV ring's manual ppermutes inside a cond would diverge the
    # collective order exactly like the TP case).
    cp = mesh.shape.get(CONTEXT_AXIS, 1)
    model_is_cp = bool(getattr(model, "context_parallel", False))
    if cp > 1 and not model_is_cp:
        raise ValueError(f"mesh has '{CONTEXT_AXIS}' size {cp} but the "
                         "model was built without context_parallel=True")
    if model_is_cp and cp <= 1:
        raise ValueError("context_parallel model needs a mesh with a "
                         f"nontrivial '{CONTEXT_AXIS}' axis")
    if cp > 1 and getattr(model, "cp_mode", "ring") == "zigzag":
        from apex_example_tpu.models.gpt import GPTForCausalLM as _GPT
        if not isinstance(model, _GPT):
            raise ValueError(
                "CP x PP zigzag is the load-balanced CAUSAL layout (gpt "
                "archs); bidirectional BERT does uniform ring work "
                "already")
    # EP x PP (round 5): switch-MoE FFNs inside the ring schedule's
    # stages — the expert all_to_all rides the manual 'data' axis inside
    # each tick, the per-(stage, microbatch) Switch aux loss rides the
    # schedule's carry (spmd_pipeline with_aux).  Expert stacks shard
    # [layers->pipe, experts->data] jointly.  moe_axis_name='data' is the
    # EP form; any UNBOUND axis name (e.g. 'expert') runs the dense-
    # reference experts on replicated stacks — the exact golden the tests
    # compare against, through this same factory.
    moe = int(getattr(model, "moe_experts", 0) or 0)
    moe_ep = moe > 0 and getattr(model, "moe_axis_name", "") == DATA_AXIS
    if moe:
        if schedule != "ring":
            raise ValueError(
                "MoE composes with the ring schedule only: the 1F1B value "
                "program would need the aux loss threaded through its "
                "masked cells and the expert all_to_all runs per tick "
                "either way (no memory win to buy)")
        if cp > 1 or tp > 1:
            raise ValueError("MoE x PP composes pairwise only (no "
                             "MoE x PP x TP/CP triple yet)")
        if moe_ep and moe % mesh.shape[DATA_AXIS]:
            raise ValueError(
                f"moe_experts={moe} must be a multiple of the data-axis "
                f"size {mesh.shape[DATA_AXIS]}")
        if moe_ep and isinstance(optimizer, PipelineFusedLAMB):
            raise ValueError(
                "PipelineFusedLAMB does not compose with EP x PP: its "
                "clip norm psums over 'pipe' only, but EP expert-stack "
                "grads vary over 'data' too — every replicated leaf would "
                "silently receive a different update per data shard")
    from apex_example_tpu.optim.fused import FusedLAMB, FusedNovoGrad
    if isinstance(optimizer, FusedLAMB):
        raise ValueError(
            "bare FusedLAMB under PP would collapse each stacked "
            "[num_layers, ...] leaf into ONE cross-layer trust ratio and "
            "clip on a stage-local grad norm; wrap it in PipelineFusedLAMB")
    if isinstance(optimizer, FusedNovoGrad):
        raise ValueError(
            "FusedNovoGrad under PP would collapse its per-TENSOR second "
            "moment (EMA of ||g||²) across each stage's stacked layers; "
            "no pipeline form exists yet")
    if isinstance(optimizer, PipelineFusedLAMB):
        # The wrapper's leading-index-dim count must match this schedule's
        # param layout: the ring pack stacks [num_layers, ...] (1 dim), the
        # 1F1B/interleaved arranged pack stacks [S, V, per, ...] (3 dims).
        # A mismatch trains silently wrong — either one trust ratio per
        # whole [V, per] block, or per-row ratios of a layout that does
        # not exist.
        want = 1 if schedule == "ring" else 3
        if optimizer.stacked_dims != want:
            raise ValueError(
                f"PipelineFusedLAMB(stacked_dims={optimizer.stacked_dims}) "
                f"does not match the {schedule!r} schedule's param layout "
                f"(needs stacked_dims={want})")
    opt = _wrap_optimizer(optimizer)
    from apex_example_tpu.models.gpt import GPTForCausalLM
    is_gpt = isinstance(model, GPTForCausalLM)
    head_sum = _gpt_head_loss_sum if is_gpt else _head_loss_sum
    layer_mod = BertLayer(model.hidden_size, model.num_heads,
                          model.intermediate_size, model.dtype,
                          model.param_dtype, model.ln_dtype,
                          model.softmax_dtype,
                          fused_attention=model.fused_attention,
                          tensor_parallel=model.tensor_parallel,
                          sequence_parallel=model.sequence_parallel,
                          context_parallel=model_is_cp,
                          cp_mode=getattr(model, "cp_mode", "ring"),
                          moe_experts=moe,
                          moe_capacity_factor=getattr(
                              model, "moe_capacity_factor", 1.25),
                          moe_axis_name=getattr(model, "moe_axis_name",
                                                "expert"),
                          moe_top_k=getattr(model, "moe_top_k", 1),
                          causal=is_gpt)
    red_axes = (DATA_AXIS, CONTEXT_AXIS) if cp > 1 else DATA_AXIS

    def _unpack(batch):
        """One schedule body serves both objectives: GPT's (x, y) pair
        becomes the MLM shape with all-ones weights, under which the
        global weighted-CE normalization IS the next-token mean."""
        if is_gpt:
            ids, labels = batch
            return ids, labels, jnp.ones(labels.shape, jnp.float32)
        ids, (labels, weights) = batch
        return ids, labels, weights

    def stage_fn(stage_layers, x):
        # stage_layers leaves: [per_stage, ...] — scan applies them in
        # order (this stage's contiguous block of encoder layers).  The
        # injected activation is pipe-invariant while the layer params
        # vary over pipe; align the scan carry's vma typing up front.
        if PIPE_AXIS not in getattr(jax.typeof(x), "vma", frozenset()):
            x = lax.pcast(x, PIPE_AXIS, to="varying")

        if moe:
            # MoE layers return (h, aux); the stage emits the SUM of its
            # layers' Switch balance losses alongside the activation
            # (spmd_pipeline with_aux accumulates it across the ring).
            def body_aux(carry, p):
                h, a = carry
                h, aux = layer_mod.apply({"params": p}, h, None)
                return (h, a + aux.astype(jnp.float32)), None
            # the aux carry must enter with the activation's shard-
            # variance type (pipe + data) or the scan carry typing trips
            a0 = lax.pcast(
                jnp.zeros((), jnp.float32),
                tuple(sorted(getattr(jax.typeof(x), "vma", frozenset()))),
                to="varying")
            (y, aux_sum), _ = lax.scan(body_aux, (x, a0), stage_layers)
            return y, aux_sum

        def body(h, p):
            return layer_mod.apply({"params": p}, h, None), None
        y, _ = lax.scan(body, x, stage_layers)
        return y

    def _split(ids):
        M = microbatches
        b = ids.shape[0]
        if b % M:
            raise ValueError(f"per-shard batch {b} not divisible by "
                             f"microbatches {M}")
        return M, b, lambda a: a.reshape(M, b // M, *a.shape[1:])

    def finish(state: TrainState, grads, loss):
        """Unscale → (all-or-none) update → scaler bookkeeping — shared by
        every schedule's per-shard step."""
        grads, grads_finite = amp_lib.unscale_grads(grads, state.scaler)
        # layers grads vary over 'pipe' (each stage owns its block), so the
        # all-leaves finite flag does too; under EP the expert-stack grads
        # additionally vary over 'data' (each shard owns its experts).
        # Make the flag mesh-invariant for the replicated metrics/scaler.
        finite_axes = (PIPE_AXIS, DATA_AXIS) if moe_ep else PIPE_AXIS
        grads_finite = lax.pmean(
            grads_finite.astype(jnp.float32), finite_axes) == 1.0
        new_params, new_opt_state = opt.apply(grads, state.opt_state,
                                              state.params)
        if policy.uses_dynamic_scaling:
            # Overflow => all-or-none skip on every stage: the flag is
            # mesh-invariant (pmean above), so each stage's where-select
            # takes the same branch and the sharded state stays consistent.
            new_params = amp_lib.select_tree(grads_finite, new_params,
                                             state.params)
            new_opt_state = amp_lib.select_tree(grads_finite, new_opt_state,
                                                state.opt_state)
        scaler = amp_lib.update_scaler(state.scaler, grads_finite)
        metrics = {"loss": loss, "scale": scaler.scale,
                   "grads_finite": grads_finite.astype(jnp.float32)}
        return TrainState(step=state.step + 1, params=new_params,
                          batch_stats=state.batch_stats,
                          opt_state=new_opt_state, scaler=scaler), metrics

    def per_shard_ring(state: TrainState, batch):
        ids, labels, weights = _unpack(batch)
        M, b, mb = _split(ids)

        def scaled_loss_fn(params):
            rest = params["rest"]
            x = _embed(rest, ids, model)          # replicated compute
            # Global masked-position denominator: per-microbatch SUMS ride
            # the schedule (scaled by M to cancel its mean), the psum stitches
            # the shards — the result equals mlm_loss on the full batch.
            denom = jnp.maximum(lax.psum(weights.sum(), red_axes), 1.0)
            out = spmd_pipeline(
                stage_fn,
                lambda y, tgt: head_sum(rest, y, tgt[0], tgt[1],
                                        model) * M / denom,
                params["layers"], mb(x), (mb(labels), mb(weights)),
                with_aux=bool(moe))
            if moe:
                loss, aux = out
                # aux: psum-over-pipe of per-(stage, microbatch) Switch
                # sums / M (spmd_pipeline) -> per-layer mean, then the
                # data-shard mean — the dense model's aux_total/L averaged
                # over routing blocks (the blocked-dense golden contract).
                aux = lax.pmean(aux / model.num_layers, DATA_AXIS)
                loss = lax.psum(loss, red_axes) \
                    + jnp.asarray(moe_aux_weight, jnp.float32) * aux
            else:
                loss = lax.psum(out, red_axes)
            return amp_lib.scale_loss(loss, state.scaler), loss

        grads, loss = jax.grad(scaled_loss_fn, has_aux=True)(state.params)
        return finish(state, grads, loss)

    def per_shard_1f1b(state: TrainState, batch):
        """True-1F1B/interleaved cell: the schedule is a VALUE program
        (manual vjp per tick), so the embedding/head backward is assembled
        around it — embed batched outside with its vjp saved, head params
        ride the loss cell, and the schedule's returned input cotangents
        close the embedding chain.  Data-axis grad reduction is implicit:
        params enter data-INVARIANT, so each vjp's AD inserts the data
        psum (safe inside the schedule's cond — the action tables vary
        over 'pipe' only, every data shard takes the same branch); the
        pipe axis, over which the predicates DO vary, is kept local and
        reduced with the two explicit psums below."""
        ids, labels, weights = _unpack(batch)
        M, b, mb = _split(ids)
        rest = state.params["rest"]
        x, vjp_embed = jax.vjp(lambda r: _embed(r, ids, model), rest)
        denom = jnp.maximum(lax.psum(weights.sum(), red_axes), 1.0)

        def last_fn(hp, y, tgt):
            raw = head_sum(hp, y, tgt[0], tgt[1], model) * M / denom
            return amp_lib.scale_loss(raw, state.scaler)

        layers = jax.tree_util.tree_map(lambda l: l[0],
                                        state.params["layers"])  # [V, …]
        if V == 1:
            layers = jax.tree_util.tree_map(lambda l: l[0], layers)
        sloss, glayers, ghead, dxa = pipeline_1f1b(
            stage_fn, last_fn, layers, mb(x),
            (mb(labels), mb(weights)), num_chunks=V, head_params=rest,
            # TP: the stage/head cells contain GSPMD model-axis collectives
            # — the cond dispatch would give devices divergent collective
            # orders and deadlock; the branch-free masked form keeps one
            # uniform order (see pipeline_1f1b docstring).  The CP KV
            # ring's manual ppermutes have the same requirement.
            uniform_collectives=tp > 1 or cp > 1)
        if V == 1:
            glayers = jax.tree_util.tree_map(lambda g: g[None], glayers)
        glayers = jax.tree_util.tree_map(lambda g: g[None], glayers)
        # Cross-pipe collection: head grads live on the last stage, input
        # cotangents on stage 0 — exact zeros elsewhere.
        ghead = jax.tree_util.tree_map(lambda g: lax.psum(g, PIPE_AXIS),
                                       ghead)
        dxa = lax.psum(dxa, PIPE_AXIS)
        (g_embed,) = vjp_embed(
            dxa.reshape(b, *x.shape[1:]).astype(x.dtype))
        grads = {"rest": jax.tree_util.tree_map(
                    lambda a, c: a + c.astype(a.dtype), ghead, g_embed),
                 "layers": glayers}
        sloss = lax.psum(sloss, red_axes)
        loss = sloss if state.scaler.identity \
            else sloss / state.scaler.scale
        return finish(state, grads, loss)

    per_shard = per_shard_ring if schedule == "ring" else per_shard_1f1b

    # Prefix specs: layers shard their stacked dim over 'pipe'; everything
    # else (embedding/head params, optimizer scalars) replicates.  The
    # optimizer state mirrors the params tree per-field
    # (engine._opt_state_specs), so the same {'rest': P(), 'layers':
    # P('pipe')} prefix applies inside each of its (mu, nu, ...) fields.
    from apex_example_tpu.engine import _opt_state_specs
    if isinstance(optimizer, PipelineZeroAdam):
        # ZeRO x PP bounds: the flat-buffer slice assumes replicated-over-
        # data, non-model-sharded stage params.
        if tp > 1 or cp > 1 or moe:
            raise ValueError("PipelineZeroAdam (ZeRO x PP) composes "
                             "pairwise only — no TP/CP/MoE triple yet")
        if optimizer.stages != S:
            raise ValueError(f"PipelineZeroAdam(stages={optimizer.stages}) "
                             f"does not match the mesh's pipe size {S}")
    if moe_ep:
        # Per-leaf specs (the prefix trick cannot single out the expert
        # stacks): abstract-init the model, pack, and mark expert leaves
        # [stacked->pipe, experts->data].
        abs_params = jax.eval_shape(
            lambda r: model.init(r, jnp.zeros((1, 8), jnp.int32)),
            jax.random.PRNGKey(0))["params"]
        abs_packed = jax.tree_util.tree_map(
            lambda sd: jax.ShapeDtypeStruct(sd.shape, sd.dtype),
            jax.eval_shape(lambda p: pack_params(p, model.num_layers),
                           abs_params))
        params_spec = {"rest": jax.tree_util.tree_map(
                           lambda _: P(), abs_packed["rest"]),
                       "layers": _moe_pp_layers_spec(abs_packed["layers"])}
        opt_spec = _opt_state_specs(optimizer, abs_packed, params_spec)
    elif isinstance(optimizer, PipelineZeroAdam):
        params_spec = {"rest": P(), "layers": P(PIPE_AXIS)}
        opt_spec = optimizer.state_spec()     # flat [S, padded] buffers
    else:
        params_spec = {"rest": P(), "layers": P(PIPE_AXIS)}
        probe = {"rest": jax.ShapeDtypeStruct((), jnp.float32),
                 "layers": jax.ShapeDtypeStruct((), jnp.float32)}
        opt_spec = _opt_state_specs(optimizer, probe, params_spec)
    state_spec = TrainState(step=P(), params=params_spec, batch_stats=P(),
                            opt_state=opt_spec, scaler=P())
    # TP×PP: manual over (pipe, data) — 'model' stays automatic so the TP
    # layers' GSPMD constraints inside the body bind to it.  CP×PP adds
    # 'context' to the MANUAL set (the KV ring's ppermutes are manual-axis
    # collectives).  The specs name manual axes; the layer leaves'
    # model-axis sharding rides along from the arrays' placement
    # (bert_pp_state_shardings).
    from apex_example_tpu.workloads import partial_manual_axis_names
    manual = frozenset({PIPE_AXIS, DATA_AXIS}
                       | ({CONTEXT_AXIS} if cp > 1 else set()))
    kw = partial_manual_axis_names(mesh, model, manual, "TP x PP")
    b = P(DATA_AXIS, CONTEXT_AXIS) if cp > 1 else P(DATA_AXIS)
    bspec = (b, b) if is_gpt else (b, (b, b))
    sharded = _shard_map(
        per_shard, mesh=mesh,
        in_specs=(state_spec, bspec),
        out_specs=(state_spec, P()), **kw)
    if cp > 1 and getattr(model, "cp_mode", "ring") == "zigzag":
        # zigzag x PP: reorder the (x, y) LM pair into the zigzag layout
        # before the shard_map, so P('context') hands device i its
        # (i, 2n-1-i) chunk pair — the same pre-pass the pure-CP GPT
        # factory applies; _embed's zigzag position ids follow.
        from apex_example_tpu.parallel.context_parallel import zigzag_shard
        inner = sharded

        def sharded(state, batch):
            x, y = batch
            return inner(state, (zigzag_shard(x, cp), zigzag_shard(y, cp)))
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())
