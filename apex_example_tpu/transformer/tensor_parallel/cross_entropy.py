"""Vocab-parallel cross entropy.

Reference (apex/transformer/tensor_parallel/cross_entropy.py, SURVEY.md
§3.2): with logits sharded along the vocab dim, compute softmax-CE without
ever materializing the full-vocab logits on one rank — local max → all-reduce
max, local Σexp → all-reduce sum, masked pick of the target logit →
all-reduce.  The backward scales exp(logit − lse) and subtracts the one-hot
on the owning shard.

TPU-native restatement, two forms:

- :func:`vocab_parallel_cross_entropy` with ``axis_name`` — the explicit
  algorithm under shard_map using ``lax.pmax``/``lax.psum``.  The backward
  falls out of differentiating the forward (every collective transposes
  correctly); no custom gradient needed.
- with ``axis_name=None`` — the GSPMD form: a numerically stable CE over
  full-shape logits whose vocab dim may be sharded by annotation; XLA keeps
  the reductions sharded and inserts the same collectives the explicit form
  spells.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["vocab_parallel_cross_entropy"]


def vocab_parallel_cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray,
                                 axis_name: Optional[str] = None,
                                 label_smoothing: float = 0.0) -> jnp.ndarray:
    """Per-token CE loss ([...,] shaped), fp32.

    With ``axis_name``: ``logits`` is THIS SHARD's slice [..., V/tp] inside a
    shard_map region; ``labels`` hold *global* vocab ids.  Without:
    ``logits`` is the full [..., V] array (possibly GSPMD-sharded).
    """
    logits = logits.astype(jnp.float32)
    if axis_name is None:
        # Full-logits form: delegate to the stock stable LSE (GSPMD keeps
        # the reduction sharded when the vocab dim is annotated sharded).
        lse = jax.nn.logsumexp(logits, axis=-1)
        target = jnp.take_along_axis(
            logits, labels[..., None], axis=-1)[..., 0]
        loss = lse - target
        if label_smoothing:
            loss = ((1.0 - label_smoothing) * loss +
                    label_smoothing * (lse - jnp.mean(logits, axis=-1)))
        return loss

    # Explicit vocab-parallel path (must run under shard_map).
    vocab_shard = logits.shape[-1]
    rank = lax.axis_index(axis_name)
    lo = rank * vocab_shard

    # Stable LSE across shards: global max via pmax, then psum of Σexp.
    # The max is a shift constant only — stop_gradient keeps it out of the
    # backward (pmax has no transpose; the reference's backward likewise
    # treats the max as a constant).
    local_max = jax.lax.stop_gradient(jnp.max(logits, axis=-1))
    gmax = lax.pmax(local_max, axis_name)
    sumexp = lax.psum(
        jnp.sum(jnp.exp(logits - gmax[..., None]), axis=-1), axis_name)
    lse = jnp.log(sumexp) + gmax

    # Target logit: owned by exactly one shard; mask + psum assembles it.
    local_ids = labels - lo
    in_range = (local_ids >= 0) & (local_ids < vocab_shard)
    safe_ids = jnp.clip(local_ids, 0, vocab_shard - 1)
    picked = jnp.take_along_axis(logits, safe_ids[..., None], axis=-1)[..., 0]
    target = lax.psum(jnp.where(in_range, picked, 0.0), axis_name)

    loss = lse - target
    if label_smoothing:
        mean_logit = lax.psum(jnp.sum(logits, axis=-1), axis_name) / (
            vocab_shard * lax.axis_size(axis_name))
        loss = ((1.0 - label_smoothing) * loss +
                label_smoothing * (lse - mean_logit))
    return loss
