"""Tensor parallelism (reference: apex/transformer/tensor_parallel/).

Two complementary realizations of the same Megatron semantics:

- :mod:`layers` — GSPMD-first modules (ColumnParallelLinear,
  RowParallelLinear, VocabParallelEmbedding): params carry full logical
  shapes with flax partitioning metadata; sharding constraints at the
  Megatron f/g points let XLA insert the ICI collectives (the TPU-idiomatic
  "annotate shardings, let the compiler place all_gather/reduce_scatter"
  recipe).
- :mod:`mappings` — the explicit collective mapping functions
  (copy/reduce/gather/scatter over the ``model`` axis, plus the
  sequence-parallel all_gather/reduce-scatter pair) for shard_map-style
  manual use, mirroring apex's autograd-function mappings one-for-one.
- :mod:`cross_entropy` — vocab-parallel cross entropy that never
  materializes the full-vocab logits on one shard.
"""

from apex_example_tpu.transformer.tensor_parallel.mappings import (  # noqa: F401
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_example_tpu.transformer.tensor_parallel.layers import (  # noqa: F401
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    param_partition_specs)
from apex_example_tpu.transformer.tensor_parallel.cross_entropy import (  # noqa: F401
    vocab_parallel_cross_entropy)

__all__ = [
    "ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding",
    "copy_to_tensor_model_parallel_region",
    "gather_from_sequence_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "param_partition_specs",
    "reduce_from_tensor_model_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
    "scatter_to_sequence_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "vocab_parallel_cross_entropy",
]
