"""Explicit TP collective mappings for shard_map-style use.

Reference (apex/transformer/tensor_parallel/mappings.py, SURVEY.md §3.2):
Megatron expresses TP as four autograd functions —

    f  = copy_to_model_parallel_region      (identity fwd, all-reduce bwd)
    g  = reduce_from_model_parallel_region  (all-reduce fwd, identity bwd)
    gather / scatter along the partitioned dim, and (sequence parallel)
    all-gather / reduce-scatter along the sequence dim.

TPU-native restatement: under ``jax.shard_map`` every one of these is a
*plain lax collective whose JAX transpose is exactly the Megatron backward*:

    pvary        ⟂ psum          (f / g pair)
    all_gather   ⟂ psum_scatter  (sequence-parallel pair)
    dynamic_slice over axis_index transposes to the masked scatter-add that
    a gather-backward is.

No hand-written custom_vjp is needed — the correctness of each backward is
guaranteed by transposition, and tests/test_transformer_parallel.py checks the
gradients against a single-device dense golden.  All functions must run
inside shard_map with ``axis_name`` bound.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from apex_example_tpu.parallel.mesh import MODEL_AXIS

__all__ = [
    "copy_to_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "gather_from_sequence_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
    "scatter_to_sequence_parallel_region",
]


def copy_to_tensor_model_parallel_region(x: jnp.ndarray,
                                         axis_name: str = MODEL_AXIS):
    """Megatron ``f``: identity forward, psum backward.

    ``lax.pcast(..., to='varying')`` marks a replicated value as
    device-varying; its transpose is psum, which is precisely the gradient
    all-reduce the reference's _CopyToModelParallelRegion.backward performs.
    """
    return lax.pcast(x, axis_name, to="varying")


def reduce_from_tensor_model_parallel_region(x: jnp.ndarray,
                                             axis_name: str = MODEL_AXIS):
    """Megatron ``g``: psum forward, identity backward."""
    return lax.psum(x, axis_name)


def gather_from_tensor_model_parallel_region(x: jnp.ndarray,
                                             axis_name: str = MODEL_AXIS,
                                             dim: int = -1):
    """All-gather shards along the partitioned (feature) dim."""
    return lax.all_gather(x, axis_name, axis=dim if dim >= 0 else
                          x.ndim + dim, tiled=True)


def scatter_to_tensor_model_parallel_region(x: jnp.ndarray,
                                            axis_name: str = MODEL_AXIS,
                                            dim: int = -1):
    """Keep this shard's chunk of the partitioned dim (fwd slice; the
    transpose is the gather the reference's backward does)."""
    dim = dim if dim >= 0 else x.ndim + dim
    world = lax.axis_size(axis_name)
    if x.shape[dim] % world:
        raise ValueError(f"dim {dim} of size {x.shape[dim]} not divisible "
                         f"by axis '{axis_name}' size {world}")
    chunk = x.shape[dim] // world
    idx = lax.axis_index(axis_name)
    return lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=dim)


def gather_from_sequence_parallel_region(x: jnp.ndarray,
                                         axis_name: str = MODEL_AXIS,
                                         seq_dim: int = 1):
    """SP → TP boundary: all-gather the sequence dim (bwd: reduce-scatter).

    Reference: sequence_parallel_enabled path in tensor_parallel/layers.py —
    activations enter a TP block sequence-sharded and are gathered right
    before the first partitioned matmul.
    """
    return lax.all_gather(x, axis_name, axis=seq_dim, tiled=True)


def reduce_scatter_to_sequence_parallel_region(x: jnp.ndarray,
                                               axis_name: str = MODEL_AXIS,
                                               seq_dim: int = 1):
    """TP → SP boundary: reduce-scatter partial sums onto sequence shards
    (bwd: all-gather).  Replaces RowParallel's trailing all-reduce when
    sequence parallelism is on — same bytes, but the result lands already
    sequence-sharded."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=seq_dim,
                            tiled=True)


def scatter_to_sequence_parallel_region(x: jnp.ndarray,
                                        axis_name: str = MODEL_AXIS,
                                        seq_dim: int = 1):
    """Split a replicated activation along the sequence dim (entry into an
    SP region from replicated land, e.g. after the embedding)."""
    return scatter_to_tensor_model_parallel_region(x, axis_name, seq_dim)
