"""GSPMD-first tensor-parallel layers.

Reference (apex/transformer/tensor_parallel/layers.py, SURVEY.md §3.2):
``ColumnParallelLinear`` (weight split along output dim; Y_i = X·A_i),
``RowParallelLinear`` (weight split along input dim; Y = Σ_i X_i·A_i, the sum
being an all-reduce), ``VocabParallelEmbedding`` (vocab rows sharded; masked
local lookup + all-reduce), and the ``sequence_parallel_enabled`` flag that
turns the row-parallel trailing all-reduce into a reduce-scatter (and the
column-parallel leading identity into an all-gather of the sequence dim).

TPU-native design — *annotate, don't orchestrate*: parameters carry full
logical shapes boxed with flax partitioning metadata
(:func:`flax.linen.with_partitioning`), activations get
``with_sharding_constraint`` at exactly the Megatron f/g points, and GSPMD
materializes the all-gather / reduce-scatter / all-reduce on ICI.  This keeps
every layer a plain function of full-shape arrays — jit-compatible on one
device (constraints are no-ops without a mesh) and parallel under a
``('pipe','data','model')`` mesh with zero code change.  The explicit
shard_map formulation of the same semantics lives in :mod:`.mappings`.

Weight init matches Megatron's "initialize the full weight, then shard"
semantics for free, because the logical weight IS full-shape.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_example_tpu import _compat
from apex_example_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from apex_example_tpu.transformer import parallel_state

Initializer = Callable[..., Any]

__all__ = ["ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "batch_axis", "constrain",
           "param_partition_specs"]


def _manual_axes() -> frozenset:
    """Mesh axes the current trace is *manual* over (bound by an enclosing
    shard_map).  Empty outside shard_map.  Constraints must not name these:
    inside the body the arrays are per-shard slices and the axis is already
    consumed by the shard_map's in_specs.  (Routed through _compat: jax
    versions without abstract meshes report no manual axes — the pure-
    GSPMD TP paths this rig runs never have any.)"""
    am = _compat.get_abstract_mesh()
    return frozenset(getattr(am, "manual_axes", ()) or ())


def constrain(x: jnp.ndarray, *spec) -> jnp.ndarray:
    """Sharding-constrain ``x`` against the current parallel_state mesh.

    No-op when no mesh is registered or every mesh axis is trivial — so the
    same model code runs single-device and under TP without branches.  An
    all-None spec is a real constraint (force replication), not a skip:
    it is how gather_output / the row-parallel reduction point are pinned.
    Axes named in the spec but absent from (or trivial in) the mesh are
    dropped, so layer code can name ``model``/``data`` unconditionally.

    Inside a *partially-manual* shard_map (the TP×PP composition: manual
    over pipe/data, auto over model) the manual axes are likewise dropped
    and the constraint binds to the trace's abstract mesh — the same layer
    code then shards only the still-automatic axes.
    """
    mesh = parallel_state.get_mesh()
    if mesh is None or all(s <= 1 for s in mesh.shape.values()):
        return x
    manual = _manual_axes()

    def live(a):
        return a if a is None or (mesh.shape.get(a, 1) > 1
                                  and a not in manual) else None

    spec = tuple(
        tuple(filter(None, (live(a) for a in e))) or None
        if isinstance(e, tuple) else live(e)
        for e in spec)
    target = _compat.get_abstract_mesh() if manual else mesh
    return jax.lax.with_sharding_constraint(x, NamedSharding(target,
                                                             P(*spec)))


def batch_axis() -> Optional[str]:
    """The data axis name if the current mesh has a nontrivial one.

    Activations in a mixed DP+TP mesh are batch-sharded over ``data``;
    constraints must say so or they would force an all-gather of the batch.
    None when the data axis is manual (shard_map already split the batch).
    """
    mesh = parallel_state.get_mesh()
    if mesh is not None and mesh.shape.get(DATA_AXIS, 1) > 1 \
            and DATA_AXIS not in _manual_axes():
        return DATA_AXIS
    return None


def param_partition_specs(variables) -> Any:
    """PartitionSpec pytree for boxed variables (feed to jit shardings /
    jax.device_put).  Thin alias of flax's get_partition_spec, re-exported so
    callers don't reach into flax.linen.spmd."""
    return nn.get_partition_spec(variables)


class ColumnParallelLinear(nn.Module):
    """Linear with the output dim sharded over the ``model`` axis.

    ``gather_output=True`` (reference default) re-replicates the output;
    ``False`` leaves it feature-sharded for a following RowParallelLinear.
    ``sequence_parallel`` marks the input as sequence-sharded (dim 1 of a
    [batch, seq, hidden] activation); the matmul against the sharded kernel
    makes GSPMD emit the sequence all-gather the reference does explicitly.
    """

    features: int
    use_bias: bool = True
    gather_output: bool = True
    sequence_parallel: bool = False
    axis_name: str = MODEL_AXIS
    dtype: Optional[jnp.dtype] = None
    param_dtype: jnp.dtype = jnp.float32
    kernel_init: Initializer = nn.initializers.lecun_normal()
    bias_init: Initializer = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel",
            nn.with_partitioning(self.kernel_init, (None, self.axis_name)),
            (x.shape[-1], self.features), self.param_dtype)
        bias = None
        if self.use_bias:
            bias = self.param(
                "bias", nn.with_partitioning(self.bias_init,
                                             (self.axis_name,)),
                (self.features,), self.param_dtype)

        b = batch_axis()
        if self.sequence_parallel and x.ndim >= 3:
            x = constrain(x, b, self.axis_name, None)
        dtype = self.dtype or x.dtype
        x = x.astype(dtype)
        y = x @ kernel.astype(dtype)
        if bias is not None:
            y = y + bias.astype(dtype)
        if self.gather_output:
            y = constrain(y, b, *([None] * (y.ndim - 1)))
        else:
            y = constrain(y, b, *([None] * (y.ndim - 2)), self.axis_name)
        return y


class RowParallelLinear(nn.Module):
    """Linear with the input dim sharded over the ``model`` axis.

    The partial products Σ over input shards become an all-reduce —
    or, with ``sequence_parallel``, a reduce-scatter onto sequence shards
    (the Megatron-SP optimization) — inserted by GSPMD at the output
    constraint.  Bias is added after the reduction (it must not be summed
    tp-times), exactly like the reference's ``skip_bias_add`` ordering.
    """

    features: int
    use_bias: bool = True
    input_is_parallel: bool = True
    sequence_parallel: bool = False
    axis_name: str = MODEL_AXIS
    dtype: Optional[jnp.dtype] = None
    param_dtype: jnp.dtype = jnp.float32
    kernel_init: Initializer = nn.initializers.lecun_normal()
    bias_init: Initializer = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel",
            nn.with_partitioning(self.kernel_init, (self.axis_name, None)),
            (x.shape[-1], self.features), self.param_dtype)
        bias = None
        if self.use_bias:
            # Replicated: applied after the cross-shard reduction.
            bias = self.param("bias", self.bias_init, (self.features,),
                              self.param_dtype)

        b = batch_axis()
        if self.input_is_parallel:
            x = constrain(x, b, *([None] * (x.ndim - 2)), self.axis_name)
        dtype = self.dtype or x.dtype
        y = x.astype(dtype) @ kernel.astype(dtype)
        if self.sequence_parallel and y.ndim >= 3:
            y = constrain(y, b, self.axis_name, None)
        else:
            y = constrain(y, b, *([None] * (y.ndim - 1)))
        if bias is not None:
            y = y + bias.astype(dtype)
        return y


class VocabParallelEmbedding(nn.Module):
    """Embedding with vocab rows sharded over the ``model`` axis.

    The reference masks ids outside the local [first, last) range, looks up
    locally, zeroes the masked rows and all-reduces.  Under GSPMD the same
    dance is the compiler's lowering of a gather from a row-sharded table;
    the output constraint decides whether it lands replicated or
    sequence-sharded (sequence_parallel).
    """

    num_embeddings: int
    features: int
    sequence_parallel: bool = False
    axis_name: str = MODEL_AXIS
    dtype: Optional[jnp.dtype] = None
    param_dtype: jnp.dtype = jnp.float32
    embedding_init: Initializer = nn.initializers.normal(stddev=0.02)

    def setup(self):
        # setup() (not @nn.compact) so ``attend`` can reuse the table — the
        # tied-decoder pattern nn.Embed supports; param name/shape match
        # nn.Embed, so checkpoints interchange with the non-TP model.
        self.embedding = self.param(
            "embedding",
            nn.with_partitioning(self.embedding_init, (self.axis_name, None)),
            (self.num_embeddings, self.features), self.param_dtype)

    def __call__(self, ids):
        y = jnp.take(self.embedding, ids, axis=0)
        if self.dtype is not None:
            y = y.astype(self.dtype)
        b = batch_axis()
        if self.sequence_parallel and y.ndim >= 3:
            y = constrain(y, b, self.axis_name, None)
        else:
            y = constrain(y, b, *([None] * (y.ndim - 1)))
        return y

    def attend(self, x):
        """Tied decoder: ``x @ table.T`` with the VOCAB dim of the logits
        sharded over the model axis (the table is row-sharded, so each shard
        produces its vocab slice locally — Megatron's parallel LM head).  A
        vocab-sharded-aware loss (XLA cross entropy under GSPMD, or
        :func:`..cross_entropy.vocab_parallel_cross_entropy` under shard_map)
        consumes the logits without re-gathering the (…, V) tensor."""
        table = self.embedding
        y = x @ table.astype(x.dtype).T
        b = batch_axis()
        return constrain(y, b, *([None] * (y.ndim - 2)), self.axis_name)
