"""Model-parallel topology bookkeeping.

Reference (apex/transformer/parallel_state.py, SURVEY.md §3.2):
``initialize_model_parallel(tp, pp)`` carves the flat NCCL world into
TP/PP/DP process groups and exposes ``get_*_world_size/rank`` getters that
the rest of apex.transformer queries.

TPU-native restatement: the "groups" are named axes of a single
:class:`jax.sharding.Mesh` built by
:func:`apex_example_tpu.parallel.mesh.initialize_model_parallel`
(pipe, data, context, model).  Sizes come from the mesh shape; ranks only exist
*inside* a shard_map/jit region where the axis is bound, via
``lax.axis_index`` — there is no process-global rank because one process
drives many devices.  The getters below accept a mesh (host side) or read the
bound axis (trace side), mirroring the reference's API names.
"""

from __future__ import annotations

from typing import Optional

from jax import lax
from jax.sharding import Mesh

from apex_example_tpu.parallel import mesh as mesh_lib
from apex_example_tpu.parallel.mesh import (CONTEXT_AXIS, DATA_AXIS,
                                            MODEL_AXIS, PIPE_AXIS)

__all__ = [
    "destroy_model_parallel",
    "initialize_model_parallel",
    "set_mesh",
    "get_mesh",
    "get_tensor_model_parallel_world_size",
    "get_pipeline_model_parallel_world_size",
    "get_data_parallel_world_size",
    "get_context_parallel_world_size",
    "get_tensor_model_parallel_rank",
    "get_pipeline_model_parallel_rank",
    "get_data_parallel_rank",
    "get_context_parallel_rank",
    "is_pipeline_first_stage",
    "is_pipeline_last_stage",
    "model_parallel_is_initialized",
]

# The most recent mesh registered via set_mesh/initialize; mirrors the
# reference's module-global group handles.
_CURRENT_MESH: Optional[Mesh] = None


def initialize_model_parallel(tensor_parallel: int = 1,
                              pipeline_parallel: int = 1,
                              context_parallel: int = 1,
                              devices=None) -> Mesh:
    """Build the (pipe, data, context, model) mesh AND register it as
    current.

    Reference parity: apex's ``initialize_model_parallel`` both builds the
    process groups and stores them in module globals that the TP/PP layers
    read — registering here keeps :func:`constrain`-based layers working
    through the same single entry point.
    """
    return set_mesh(mesh_lib.initialize_model_parallel(
        tensor_parallel, pipeline_parallel, context_parallel,
        devices=devices))


def set_mesh(mesh: Optional[Mesh]) -> Optional[Mesh]:
    """Register (or, with None, clear) the current model-parallel mesh."""
    global _CURRENT_MESH
    _CURRENT_MESH = mesh
    return mesh


def get_mesh() -> Optional[Mesh]:
    return _CURRENT_MESH


def model_parallel_is_initialized() -> bool:
    return _CURRENT_MESH is not None


def destroy_model_parallel() -> None:
    """Reference-parity teardown: forget the registered mesh."""
    set_mesh(None)


def _axis_size(axis: str, mesh: Optional[Mesh]) -> int:
    mesh = mesh or _CURRENT_MESH
    if mesh is not None and axis in mesh.shape:
        return mesh.shape[axis]
    # Trace side: axis bound by an enclosing shard_map.
    try:
        return lax.axis_size(axis)
    except (NameError, KeyError):
        return 1


def get_tensor_model_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(MODEL_AXIS, mesh)


def get_pipeline_model_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(PIPE_AXIS, mesh)


def get_data_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(DATA_AXIS, mesh)


def get_context_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(CONTEXT_AXIS, mesh)


def get_tensor_model_parallel_rank():
    """Rank along the model axis — valid only inside shard_map (traced)."""
    return lax.axis_index(MODEL_AXIS)


def get_pipeline_model_parallel_rank():
    return lax.axis_index(PIPE_AXIS)


def get_data_parallel_rank():
    return lax.axis_index(DATA_AXIS)


def get_context_parallel_rank():
    return lax.axis_index(CONTEXT_AXIS)


def is_pipeline_first_stage():
    return lax.axis_index(PIPE_AXIS) == 0


def is_pipeline_last_stage():
    return lax.axis_index(PIPE_AXIS) == lax.axis_size(PIPE_AXIS) - 1
