"""Expert parallelism (MoE): top-1 (Switch) / top-2 (GShard) routing over an
expert axis.

Reference status: EP is ABSENT from the reference family (SURVEY.md §3.2
marks it "documented as absent"); like context parallelism
(parallel/context_parallel.py) this is a TPU-first extension beyond the
reference, built because the mesh/collective machinery makes it natural and
a "complete" modern parallelism surface includes it.

TPU-native design (the Switch-Transformer dispatch, expressed as static-shape
XLA collectives — no dynamic shapes, jit-stable):

  1. router: logits = x @ w_r → top-1 expert per token, softmax gate
     (top_k=2: GShard-style second choice with renormalized gates).
  2. capacity: each expert accepts at most C tokens per device
     (C = ceil(tokens/E · capacity_factor)); overflow tokens are dropped
     (their combine weight is 0 — the standard switch trade that keeps every
     shape static).
  3. dispatch: one-hot position-in-expert (cumsum over the token dim) builds
     a [E, C, d] buffer per device; ``lax.all_to_all`` over the expert axis
     turns it into this device's experts' per-sender token blocks.
  4. expert FFN (dense→act→dense; k = E/n experts per device shard,
     batched over the local expert dim).
  5. inverse all_to_all + gate-weighted combine back to [tokens, d].

Gradients flow through dispatch/combine as through any other collectives
(all_to_all transposes to the inverse all_to_all).  A load-balancing aux
loss (mean fraction·prob product, Switch eq. 4) is returned for the trainer
to weight.

``EXPERT_AXIS = "expert"``; run inside shard_map with tokens sharded over
the axis (typically the same devices as data parallelism — EP reuses the DP
axis the way DeepSpeed-MoE does).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import flax.linen as nn

import jax
import jax.numpy as jnp
from jax import lax

EXPERT_AXIS = "expert"


class MoEParams(NamedTuple):
    w_router: jnp.ndarray   # [d, E] (replicated)
    w_in: jnp.ndarray       # stacked [E, d, h]; [k, d, h] local shard
    w_out: jnp.ndarray      # stacked [E, h, d]; [k, h, d] local shard


def init_moe_params(rng, d: int, hidden: int, n_experts: int,
                    dtype=jnp.float32) -> MoEParams:
    """Logical params: router replicated, expert weights stacked [E, ...]
    and sharded over the expert axis (P(expert) on dim 0 → E/n experts
    per device at the shard_map boundary)."""
    k1, k2, k3 = jax.random.split(rng, 3)
    scale = 1.0 / jnp.sqrt(d)
    return MoEParams(
        w_router=(jax.random.normal(k1, (d, n_experts)) * scale
                  ).astype(dtype),
        w_in=(jax.random.normal(k2, (n_experts, d, hidden)) * scale
              ).astype(dtype),
        w_out=(jax.random.normal(k3, (n_experts, hidden, d)) * scale
               ).astype(dtype))


def _dispatch_masks(logits: jnp.ndarray, capacity: int, top_k: int = 1
                    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-1 (Switch) or top-2 (GShard-style) dispatch for [T, E] router
    logits.

    Returns (dispatch [T, E, C] one-hot, combine [T, E, C] gate-weighted,
    aux_loss scalar).  All shapes static; overflow tokens get all-zero
    rows.  Top-2 follows the GShard conventions: the two gates are
    renormalized to sum to 1, second choices queue BEHIND every kept
    first choice in each expert's capacity buffer (so under pressure the
    second opinions are the ones dropped), and the load-balancing loss
    keys on the FIRST-choice assignment fractions.
    """
    T, E = logits.shape
    if top_k not in (1, 2):
        raise ValueError(f"top_k must be 1 or 2, got {top_k}")
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    e1 = jnp.argmax(probs, axis=-1)                      # [T]
    g1 = jnp.take_along_axis(probs, e1[:, None], axis=-1)[:, 0]
    oh1 = jax.nn.one_hot(e1, E, dtype=jnp.float32)       # [T, E]

    # position of each first-choice token within its expert's queue
    pos1 = jnp.cumsum(oh1, axis=0) * oh1 - 1.0           # [T, E]
    keep1 = (pos1 < capacity) & (oh1 > 0)
    pc1 = jax.nn.one_hot(pos1.astype(jnp.int32), capacity,
                         dtype=jnp.float32)              # [T, E, C]
    d1 = pc1 * keep1[..., None]

    # Switch load-balancing loss: E · Σ_e fraction_e · mean-prob_e
    # (first-choice fractions in both modes).
    aux = E * jnp.sum(oh1.mean(axis=0) * probs.mean(axis=0))

    if top_k == 1:
        return d1, d1 * g1[:, None, None], aux

    e2 = jnp.argmax(probs - oh1 * 2.0, axis=-1)          # runner-up
    g2 = jnp.take_along_axis(probs, e2[:, None], axis=-1)[:, 0]
    oh2 = jax.nn.one_hot(e2, E, dtype=jnp.float32)
    # second choices start after each expert's KEPT first-choice count
    used1 = jnp.minimum(oh1.sum(axis=0), float(capacity))    # [E]
    pos2 = jnp.cumsum(oh2, axis=0) * oh2 - 1.0 + used1[None] * oh2
    keep2 = (pos2 < capacity) & (oh2 > 0)
    pc2 = jax.nn.one_hot(pos2.astype(jnp.int32), capacity,
                         dtype=jnp.float32)
    d2 = pc2 * keep2[..., None]

    denom = jnp.maximum(g1 + g2, 1e-9)
    combine = (d1 * (g1 / denom)[:, None, None]
               + d2 * (g2 / denom)[:, None, None])
    return d1 + d2, combine, aux


def moe_forward(params: MoEParams, x: jnp.ndarray,
                capacity_factor: float = 1.25,
                axis_name: str = EXPERT_AXIS,
                activation=jax.nn.relu,
                top_k: int = 1) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Switch-MoE block over the expert axis.  Inside shard_map:

    x: [T, d] this device's tokens; params.w_in/w_out:
    [k, d, h]/[k, h, d] — this device's k = E/n experts of the stacked
    [E, ...] arrays (P(axis) on dim 0).

    Returns (y [T, d], aux_loss).
    """
    T, d = x.shape
    n = lax.axis_size(axis_name)
    k = params.w_in.shape[0]            # experts on THIS device
    E = params.w_router.shape[1]        # total experts
    # k experts per expert-axis device (E = k·n): the [E, C, d] send
    # buffer is split n-ways by the tiled all_to_all, so router width,
    # axis size, and the local weight shard must agree or every device
    # silently applies the wrong experts to other experts' tokens.
    if E != k * n:
        raise ValueError(
            f"moe_forward needs n_experts == local shard x axis size; got "
            f"router width {E}, axis '{axis_name}' size {n}, local shard "
            f"{k} (shard stacked [E, ...] weights with P('{axis_name}'))")
    # GShard capacity sizing: the dispatch demand is top_k slots per
    # token, so C scales with top_k or most second choices would be
    # silently dropped at the default factor.
    capacity = int(-(-T * top_k * capacity_factor // E))
    # lane-friendly capacity (C is a matmul/all_to_all dim)
    capacity = capacity + (-capacity) % 8
    C = capacity

    logits = x @ params.w_router.astype(x.dtype)         # [T, E]
    dispatch, combine, aux = _dispatch_masks(logits, capacity, top_k)

    # [E, C, d] expert-major send buffer; the tiled all_to_all splits it
    # into n k-expert blocks and swaps "which expert block" for "which
    # sender": recv row j·k+e = device j's tokens for THIS device's local
    # expert e.
    send = jnp.einsum("td,tec->ecd", x.astype(jnp.float32),
                      dispatch).astype(x.dtype)
    recv = lax.all_to_all(send, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)                    # [n·k, C, d]
    # group by local expert: [n, k, C, d] -> [k, n·C, d]
    recv = recv.reshape(n, k, C, d).transpose(1, 0, 2, 3) \
               .reshape(k, n * C, d)
    w_in = params.w_in.astype(x.dtype)                   # [k, d, h]
    w_out = params.w_out.astype(x.dtype)                 # [k, h, d]
    h = activation(jnp.einsum("kcd,kdh->kch", recv, w_in))
    out = jnp.einsum("kch,khd->kcd", h, w_out)           # [k, n·C, d]
    # back to sender-major [n·k, C, d] for the inverse all_to_all
    out = out.reshape(k, n, C, d).transpose(1, 0, 2, 3) \
             .reshape(n * k, C, d)
    back = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)                    # [E, C, d]
    y = jnp.einsum("ecd,tec->td", back.astype(jnp.float32),
                   combine).astype(x.dtype)
    return y, lax.pmean(aux, axis_name)


def moe_forward_dense_reference(params: MoEParams, x: jnp.ndarray,
                                capacity_factor: float = 1.25,
                                activation=jax.nn.relu,
                                top_k: int = 1
                                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """No-mesh golden: every expert computed densely on every token, the
    same dispatch/combine masks select the result.  Matches moe_forward
    exactly on a single shard (tests) and defines the semantics."""
    T, d = x.shape
    E = params.w_in.shape[0]
    capacity = int(-(-T * top_k * capacity_factor // E))
    capacity = capacity + (-capacity) % 8

    logits = x @ params.w_router.astype(x.dtype)
    dispatch, combine, aux = _dispatch_masks(logits, capacity, top_k)

    send = jnp.einsum("td,tec->ecd", x.astype(jnp.float32),
                      dispatch).astype(x.dtype)           # [E, C, d]
    h = activation(jnp.einsum("ecd,edh->ech", send,
                              params.w_in.astype(x.dtype)))
    out = jnp.einsum("ech,ehd->ecd", h, params.w_out.astype(x.dtype))
    y = jnp.einsum("ecd,tec->td", out.astype(jnp.float32),
                   combine).astype(x.dtype)
    return y, aux


def _axis_is_bound(axis_name: str) -> bool:
    """Trace-time: is ``axis_name`` a live manual mesh axis here?

    Lets one module body serve both worlds: under the EP shard_map the
    collectives run; in eager/plain-jit contexts (init, dense eval, the
    golden tests) the dense reference runs.  Resolution happens at trace
    time, so jit sees a single static branch.
    """
    try:
        lax.axis_size(axis_name)
        return True
    except NameError:
        return False


class MoEMLP(nn.Module):
    """Switch-MoE replacement for a transformer FFN block (flax).

    Logical params: router [d, E], stacked expert weights w_in [E, d, h] /
    w_out [E, h, d].  Outside any mesh the dense reference runs on the full
    stack (init, golden tests, single-device eval).  Inside a shard_map
    with ``axis_name`` bound, the caller shards the stacked weights over
    that axis (P(axis) on dim 0 — E/n experts per device; see
    ``workloads.bert_moe_state_specs``) and the all_to_all dispatch runs.

    Returns ``(y, aux)`` — the load-balancing aux loss is part of the
    training objective (Switch eq. 4), so it is returned rather than sown:
    the model's output contract carries it to the loss function explicitly.
    """

    hidden_size: int
    intermediate_size: int
    n_experts: int
    capacity_factor: float = 1.25
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    axis_name: str = EXPERT_AXIS
    top_k: int = 1

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        d, h, E = self.hidden_size, self.intermediate_size, self.n_experts
        init = nn.initializers.normal(1.0 / float(d) ** 0.5)
        dist = _axis_is_bound(self.axis_name)
        # flax verifies declared param shapes against the provided values
        # at apply time; inside the EP shard_map the stacked [E, ...]
        # arrays arrive SLICED to this device's experts (E/n of them), so
        # the declared leading dim is the local one.  Init always runs
        # outside the mesh (dist=False) and stores the full stack.
        e_local = E // lax.axis_size(self.axis_name) if dist else E
        params = MoEParams(
            w_router=self.param("router", init, (d, E), self.param_dtype),
            w_in=self.param("w_in", init, (e_local, d, h),
                            self.param_dtype),
            w_out=self.param("w_out", init, (e_local, h, d),
                             self.param_dtype))
        flat = x.reshape(-1, d).astype(self.dtype)
        if dist:
            y, aux = moe_forward(params, flat, self.capacity_factor,
                                 self.axis_name, activation=nn.gelu,
                                 top_k=self.top_k)
        else:
            y, aux = moe_forward_dense_reference(
                params, flat, self.capacity_factor, activation=nn.gelu,
                top_k=self.top_k)
        return y.reshape(x.shape).astype(self.dtype), aux
