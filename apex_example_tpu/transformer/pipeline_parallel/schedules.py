"""Pipeline schedules.

Reference (apex/transformer/pipeline_parallel/schedules/, SURVEY.md §3.2):
three schedules — ``forward_backward_no_pipelining`` (serial microbatches
with grad accumulation), 1F1B without interleaving, and the
interleaved-virtual-stage variant.  Each manually orchestrates
forward/backward passes and isend/irecv pairs per microbatch.

TPU-native restatement: a schedule is a *traced collective program*, not an
orchestration loop.  ``spmd_pipeline`` runs the classic SPMD ring pipeline —
``lax.scan`` over ticks, each tick computing one stage-step on every device
and rotating activations with ``ppermute`` — and gets its backward schedule
from autodiff (the transpose of the scan runs the ticks reversed with the
reverse rotation, i.e. the backward pipeline).  ``jax.checkpoint`` around the
stage body keeps live memory to one activation per in-flight microbatch,
which is the same peak-memory class 1F1B targets; the steady-state
compute/communication overlap is XLA's latency-hiding scheduler's job.  The
reference's entry-point names are preserved; the semantic delta (autodiff
chooses the fwd/bwd interleaving, not the host) is documented here rather
than hidden.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_example_tpu.parallel.mesh import PIPE_AXIS
from apex_example_tpu.transformer.pipeline_parallel.p2p_communication import (
    send_forward)

__all__ = ["forward_backward_no_pipelining",
           "forward_backward_pipelining_without_interleaving",
           "spmd_pipeline"]


def forward_backward_no_pipelining(
        loss_fn: Callable[[Any, Any], jnp.ndarray],
        params: Any,
        microbatches: Any,
) -> Tuple[jnp.ndarray, Any]:
    """Grad accumulation over microbatches, no stage parallelism.

    ``microbatches`` is a pytree whose leaves have a leading microbatch dim
    [M, ...]; ``loss_fn(params, mb) -> scalar``.  Returns (mean loss, mean
    grads) — the reference's schedule likewise averages losses/grads over
    microbatches before the optimizer step.
    """
    m = jax.tree_util.tree_leaves(microbatches)[0].shape[0]
    zeros = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)

    def body(carry, mb):
        loss_sum, grad_sum = carry
        loss, grads = jax.value_and_grad(loss_fn)(params, mb)
        # Accumulate into ONE fp32 buffer (the reference accumulates grads
        # in place across microbatches; stacking M copies would defeat the
        # memory purpose of microbatching).
        grad_sum = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(jnp.float32), grad_sum, grads)
        return (loss_sum + loss, grad_sum), None

    (loss_sum, grad_sum), _ = lax.scan(
        body, (jnp.zeros((), jnp.float32), zeros), microbatches)
    grads = jax.tree_util.tree_map(
        lambda a, p: (a / m).astype(p.dtype), grad_sum, params)
    return loss_sum / m, grads


def spmd_pipeline(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                  last_stage_fn: Callable[[jnp.ndarray, Any], jnp.ndarray],
                  stage_params: Any,
                  inputs: jnp.ndarray,
                  targets: Any,
                  axis_name: str = PIPE_AXIS,
                  remat: bool = True) -> jnp.ndarray:
    """Mean loss of the ring pipeline; differentiate for the full schedule.

    Must run inside shard_map with ``axis_name`` bound.  Arguments:

    - ``stage_fn(stage_params, x) -> y``: one stage's forward on one
      microbatch (this device's slice of the layer stack).
    - ``last_stage_fn(y, target) -> scalar loss`` for one microbatch.
    - ``stage_params``: THIS stage's params (shard_map splits the stacked
      stage dim via in_specs).
    - ``inputs``: [M, ...] microbatched model inputs — a single array whose
      per-microbatch shape equals the inter-stage activation shape (the ring
      carry is one buffer; embed to activation shape before the pipeline).
      Replicated; only the first stage reads it.
    - ``targets``: [M, ...] microbatched labels, any pytree (only the last
      stage reads them).

    Tick t: stage s processes microbatch t−s; stage 0 injects microbatch t;
    the last stage scores microbatch t−(S−1) once t ≥ S−1.  T = M+S−1 ticks
    drain the pipe.  Bubble ticks compute on don't-care data and are masked
    out of the loss — the standard SPMD-pipeline trade (S−1 wasted
    stage-steps) that keeps the whole schedule one fused collective program.
    """
    if not isinstance(inputs, jnp.ndarray):
        raise TypeError("spmd_pipeline inputs must be a single [M, ...] "
                        "array matching the inter-stage activation shape; "
                        f"got {type(inputs).__name__}")
    S = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = inputs.shape[0]
    T = M + S - 1

    body = jax.checkpoint(stage_fn) if remat else stage_fn

    def pick(stack, t):
        # Clamp: bubble ticks re-read an arbitrary microbatch; masked later.
        return jax.tree_util.tree_map(
            lambda s: lax.dynamic_index_in_dim(
                s, jnp.clip(t, 0, M - 1), keepdims=False), stack)

    def compute(recv, loss_acc, t):
        """One tick given the activation received from upstream."""
        # First stage injects a fresh microbatch; others consume the ring.
        x = jnp.where(idx == 0, pick(inputs, t), recv)
        y = body(stage_params, x)
        # Last stage scores microbatch t-(S-1) when it is real.
        mb = t - (S - 1)
        loss_t = last_stage_fn(y, pick(targets, mb))
        use = (idx == S - 1) & (mb >= 0)
        return y, loss_acc + jnp.where(use, loss_t, 0.0)

    # Tick 0 needs no upstream receive (the pipe is empty); the remaining
    # ticks rotate at entry via p2p send_forward, so no final rotation is
    # computed only to be discarded.
    x0 = pick(inputs, jnp.asarray(0))
    out_sd = jax.eval_shape(stage_fn, stage_params, x0)
    empty = lax.pcast(jnp.zeros(out_sd.shape, out_sd.dtype), axis_name,
                      to="varying")
    loss0 = lax.pcast(jnp.zeros((), jnp.float32), axis_name, to="varying")
    y, loss_acc = compute(empty, loss0, jnp.asarray(0))

    def tick(carry, t):
        y, loss_acc = carry
        y, loss_acc = compute(send_forward(y, axis_name), loss_acc, t)
        return (y, loss_acc), None

    (_, loss_sum), _ = lax.scan(tick, (y, loss_acc), jnp.arange(1, T))
    # Only the last stage accumulated anything; psum makes the mean loss a
    # cross-stage invariant (and its transpose routes the cotangent there).
    return lax.psum(loss_sum, axis_name) / M


def forward_backward_pipelining_without_interleaving(
        stage_fn, last_stage_fn, stage_params, inputs, targets,
        axis_name: str = PIPE_AXIS, remat: bool = True,
) -> Tuple[jnp.ndarray, Any]:
    """(loss, grads-wrt-stage_params) of the ring pipeline.

    Reference-name parity for the 1F1B schedule; see module docstring for
    the honest scheduling delta.
    """
    def f(p):
        return spmd_pipeline(stage_fn, last_stage_fn, p, inputs, targets,
                             axis_name=axis_name, remat=remat)
    return jax.value_and_grad(f)(stage_params)
