"""Pipeline schedules.

Reference (apex/transformer/pipeline_parallel/schedules/, SURVEY.md §3.2):
three schedules — ``forward_backward_no_pipelining`` (serial microbatches
with grad accumulation), 1F1B without interleaving, and the
interleaved-virtual-stage variant.  Each manually orchestrates
forward/backward passes and isend/irecv pairs per microbatch.

TPU-native restatement: a schedule is a *traced collective program*, not an
orchestration loop.  Two families are provided:

- ``spmd_pipeline``: the classic SPMD ring pipeline — ``lax.scan`` over
  ticks, each tick computing one stage-step on every device and rotating
  activations with ``ppermute`` — whose backward schedule comes from
  autodiff (the transpose of the scan runs the ticks reversed with the
  reverse rotation).  Simplest program, best XLA overlap; but the scan
  transpose stores one carry per tick, so live activations grow with M.
- ``pipeline_1f1b`` (and the reference-named wrappers
  ``forward_backward_pipelining_without_interleaving`` /
  ``_with_interleaving``): TRUE 1F1B — a static per-tick action table
  (warmup forwards, steady 1F/1B alternation, drain) drives masked
  forward/backward compute, bounding in-flight activations to ≤ S
  microbatch inputs per stage regardless of M.

Bubble accounting: both forms pay the same tick bubble
(S−1)/(M+S−1) per direction; 1F1B's win is the M-independent activation
memory, and the interleaved variant trades (V−1)·S extra warmup depth for
a ≈V× smaller bubble — the reference's tradeoff, reproduced.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_example_tpu.parallel.mesh import PIPE_AXIS
from apex_example_tpu.transformer.pipeline_parallel.p2p_communication import (
    send_backward, send_forward)

__all__ = ["forward_backward_no_pipelining",
           "forward_backward_pipelining_without_interleaving",
           "forward_backward_pipelining_with_interleaving",
           "pipeline_1f1b", "spmd_pipeline"]


def forward_backward_no_pipelining(
        loss_fn: Callable[[Any, Any], jnp.ndarray],
        params: Any,
        microbatches: Any,
) -> Tuple[jnp.ndarray, Any]:
    """Grad accumulation over microbatches, no stage parallelism.

    ``microbatches`` is a pytree whose leaves have a leading microbatch dim
    [M, ...]; ``loss_fn(params, mb) -> scalar``.  Returns (mean loss, mean
    grads) — the reference's schedule likewise averages losses/grads over
    microbatches before the optimizer step.
    """
    m = jax.tree_util.tree_leaves(microbatches)[0].shape[0]
    zeros = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)

    def body(carry, mb):
        loss_sum, grad_sum = carry
        loss, grads = jax.value_and_grad(loss_fn)(params, mb)
        # Accumulate into ONE fp32 buffer (the reference accumulates grads
        # in place across microbatches; stacking M copies would defeat the
        # memory purpose of microbatching).
        grad_sum = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(jnp.float32), grad_sum, grads)
        return (loss_sum + loss, grad_sum), None

    (loss_sum, grad_sum), _ = lax.scan(
        body, (jnp.zeros((), jnp.float32), zeros), microbatches)
    grads = jax.tree_util.tree_map(
        lambda a, p: (a / m).astype(p.dtype), grad_sum, params)
    return loss_sum / m, grads


def spmd_pipeline(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                  last_stage_fn: Callable[[jnp.ndarray, Any], jnp.ndarray],
                  stage_params: Any,
                  inputs: jnp.ndarray,
                  targets: Any,
                  axis_name: str = PIPE_AXIS,
                  remat: bool = True,
                  with_aux: bool = False):
    """Mean loss of the ring pipeline; differentiate for the full schedule.

    Must run inside shard_map with ``axis_name`` bound.  Arguments:

    - ``stage_fn(stage_params, x) -> y``: one stage's forward on one
      microbatch (this device's slice of the layer stack).
    - ``last_stage_fn(y, target) -> scalar loss`` for one microbatch.
    - ``stage_params``: THIS stage's params (shard_map splits the stacked
      stage dim via in_specs).
    - ``inputs``: [M, ...] microbatched model inputs — a single array whose
      per-microbatch shape equals the inter-stage activation shape (the ring
      carry is one buffer; embed to activation shape before the pipeline).
      Replicated; only the first stage reads it.
    - ``targets``: [M, ...] microbatched labels, any pytree (only the last
      stage reads them).

    Tick t: stage s processes microbatch t−s; stage 0 injects microbatch t;
    the last stage scores microbatch t−(S−1) once t ≥ S−1.  T = M+S−1 ticks
    drain the pipe.  Bubble ticks compute on don't-care data and are masked
    out of the loss — the standard SPMD-pipeline trade (S−1 wasted
    stage-steps) that keeps the whole schedule one fused collective program.

    ``with_aux=True`` (the EP x PP composition): ``stage_fn`` returns
    ``(y, aux_scalar)`` — a per-(stage, microbatch) auxiliary scalar (the
    Switch load-balancing loss summed over this stage's MoE layers) — and
    the schedule returns ``(mean loss, aux_sum)``: the psum over stages
    of every VALID tick's aux (bubble ticks masked exactly like the
    loss), divided by M.  The caller normalizes by its layer count and
    weights it into the objective; gradients flow through the aux path
    because the accumulation lives inside the differentiated program.
    """
    if not isinstance(inputs, jnp.ndarray):
        raise TypeError("spmd_pipeline inputs must be a single [M, ...] "
                        "array matching the inter-stage activation shape; "
                        f"got {type(inputs).__name__}")
    S = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = inputs.shape[0]
    T = M + S - 1

    body = jax.checkpoint(stage_fn) if remat else stage_fn

    def pick(stack, t):
        # Clamp: bubble ticks re-read an arbitrary microbatch; masked later.
        return jax.tree_util.tree_map(
            lambda s: lax.dynamic_index_in_dim(
                s, jnp.clip(t, 0, M - 1), keepdims=False), stack)

    def compute(recv, loss_acc, aux_acc, t):
        """One tick given the activation received from upstream."""
        # First stage injects a fresh microbatch; others consume the ring.
        x = jnp.where(idx == 0, pick(inputs, t), recv)
        if with_aux:
            y, aux_t = body(stage_params, x)
            # Stage s at tick t holds microbatch t - s; bubble ticks
            # (outside [0, M)) computed don't-care routing — mask them.
            mine = t - idx
            aux_acc = aux_acc + jnp.where((mine >= 0) & (mine < M),
                                          aux_t.astype(jnp.float32), 0.0)
        else:
            y = body(stage_params, x)
        # Last stage scores microbatch t-(S-1) when it is real.
        mb = t - (S - 1)
        loss_t = last_stage_fn(y, pick(targets, mb))
        use = (idx == S - 1) & (mb >= 0)
        return y, loss_acc + jnp.where(use, loss_t, 0.0), aux_acc

    # Tick 0 needs no upstream receive (the pipe is empty); the remaining
    # ticks rotate at entry via p2p send_forward, so no final rotation is
    # computed only to be discarded.
    x0 = pick(inputs, jnp.asarray(0))
    out_sd = jax.eval_shape(stage_fn, stage_params, x0)
    if with_aux:
        out_sd = out_sd[0]
    empty = lax.pcast(jnp.zeros(out_sd.shape, out_sd.dtype), axis_name,
                      to="varying")
    loss0 = lax.pcast(jnp.zeros((), jnp.float32), axis_name, to="varying")
    aux0 = lax.pcast(jnp.zeros((), jnp.float32), axis_name, to="varying")
    y, loss_acc, aux_acc = compute(empty, loss0, aux0, jnp.asarray(0))

    def tick(carry, t):
        y, loss_acc, aux_acc = carry
        y, loss_acc, aux_acc = compute(send_forward(y, axis_name),
                                       loss_acc, aux_acc, t)
        return (y, loss_acc, aux_acc), None

    (_, loss_sum, aux_sum), _ = lax.scan(tick, (y, loss_acc, aux_acc),
                                         jnp.arange(1, T))
    # Only the last stage accumulated anything; psum makes the mean loss a
    # cross-stage invariant (and its transpose routes the cotangent there).
    loss = lax.psum(loss_sum, axis_name) / M
    if with_aux:
        return loss, lax.psum(aux_sum, axis_name) / M
    return loss


# ---------------------------------------------------------------------------
# True 1F1B (and interleaved-virtual-stage) schedules
# ---------------------------------------------------------------------------

def _simulate_1f1b(M: int, S: int, V: int = 1):
    """Lockstep simulation of the 1F1B schedule → per-tick action tables.

    Builds each device's action sequence (warmup forwards, steady-state
    F/B alternation, drain backwards — the reference schedule's structure;
    for V>1 the interleaved order: microbatches in groups of S, chunks
    cycled per group, warmup 2(S−1−s)+(V−1)S), then advances a global tick
    clock where an action runs only when its producer finished on an
    EARLIER tick (one-ring-hop latency).  Returns ``(fwd_tbl, bwd_tbl)``
    as [T][S] lists of encoded actions (chunk·M + microbatch, −1 = idle).

    The simulation also proves the runtime's fixed-size buffers safe for
    this (M, S, V): each (device, chunk) forward/backward message register
    is single-slot, and the per-(device, chunk) input stash has S slots
    reused mod S — any schedule that would overwrite an unconsumed value
    fails loudly here at trace time instead of corrupting data.
    """
    if V > 1 and M % S != 0:
        raise ValueError(
            f"interleaved schedule needs microbatches ({M}) divisible by "
            f"pipeline stages ({S})")
    total = M * V

    def fwd_order(i):
        group, r = divmod(i, S * V)
        return r // S, group * S + r % S            # (chunk, microbatch)

    def bwd_order(i):
        group, r = divmod(i, S * V)
        return V - 1 - r // S, group * S + r % S

    # Per-device F and B sequences.  Unlike the reference's one-op-per-tick
    # host schedule, each SPMD tick has an F slot AND a B slot: in the
    # steady state a stage runs its next forward and its next backward in
    # the same tick (masked compute executes both paths anyway, and even
    # under real control flow a combined tick costs exactly what two
    # serial ticks would).  The 1F1B memory bound is kept by capping
    # produced-but-unretired forwards at the warmup window.
    fseqs, bseqs, caps = [], [], []
    for s in range(S):
        if V == 1:
            w = min(S - 1 - s, M)
        else:
            w = min(2 * (S - 1 - s) + (V - 1) * S, total)
        fseqs.append([fwd_order(i) for i in range(total)])
        bseqs.append([bwd_order(i) for i in range(total)])
        caps.append(w + 1)

    done_f = {}          # (device, chunk, mb) -> completion tick
    done_b = {}
    fptr = [0] * S
    bptr = [0] * S
    fwd_tbl, bwd_tbl = [], []
    t = 0
    while any(fptr[s] < total or bptr[s] < total for s in range(S)):
        if t > 8 * (total + S) + 16:     # deadlock guard
            raise AssertionError("1F1B simulation did not converge")
        frow, brow = [-1] * S, [-1] * S
        for s in range(S):
            # B slot first: a backward retiring this tick frees its
            # in-flight slot for this tick's forward (the runtime reads
            # the stash before the forward overwrites it).
            if bptr[s] < total:
                c, k = bseqs[s][bptr[s]]
                j = c * S + s
                if j == V * S - 1:
                    ok = done_f.get((s, c, k), t) < t
                else:
                    nxt = ((s + 1) % S, c if s < S - 1 else c + 1, k)
                    ok = done_b.get(nxt, t) < t
                if ok:
                    brow[s] = c * M + k
                    done_b[(s, c, k)] = t
                    bptr[s] += 1
            if fptr[s] < total and fptr[s] - bptr[s] < caps[s]:
                c, k = fseqs[s][fptr[s]]
                j = c * S + s            # global stage index
                # producer of my chunk-c input: (device s-1, same chunk),
                # wrapping to (device S-1, chunk c-1) at the ring seam.
                ok = (j == 0) or (done_f.get(((s - 1) % S,
                                              c if s > 0 else c - 1, k),
                                             t) < t)
                if ok:
                    frow[s] = c * M + k
                    done_f[(s, c, k)] = t
                    fptr[s] += 1
        fwd_tbl.append(frow)
        bwd_tbl.append(brow)
        t += 1

    # Register sizing + safety proofs.  A (device, chunk) message stream is
    # FIFO in the microbatch index, so the slot file keyed k mod depth is
    # safe iff consumption of k happens no later than production of k+depth;
    # the minimal depth is the peak produced-but-unconsumed count.
    def _depth(done_prod, done_cons, prod_of):
        need = 1
        for s in range(S):
            for c in range(V):
                ps, pc = prod_of(s, c)
                events = []
                for k in range(M):
                    if (ps, pc, k) in done_prod and (s, c, k) in done_cons:
                        # produced at END of its tick, freed at START of the
                        # consuming tick — same-tick consume-then-produce
                        # reuses the slot.
                        events.append((done_prod[(ps, pc, k)] + 0.9, +1))
                        events.append((done_cons[(s, c, k)] + 0.1, -1))
                live = peak = 0
                for _, delta in sorted(events):
                    live += delta
                    peak = max(peak, live)
                need = max(need, peak)
                # safety with the chosen keying: cons(k) <= prod(k+need)
                for k in range(M - need):
                    if (ps, pc, k + need) in done_prod \
                            and (s, c, k) in done_cons:
                        assert done_cons[(s, c, k)] <= \
                            done_prod[(ps, pc, k + need)], \
                            f"register clobbered at stage {s} chunk {c}"
        return need

    fdepth = _depth(done_f, done_f,
                    lambda s, c: ((s - 1) % S, c if s > 0 else c - 1))
    bdepth = _depth(done_b, done_b,
                    lambda s, c: ((s + 1) % S, c if s < S - 1 else c + 1))
    # Input stash: produced by my own F, consumed by my own B.  Its depth is
    # the peak number of in-flight microbatches per (stage, chunk) — S-1-s+1
    # for plain 1F1B, larger under interleaving.
    xdepth = _depth(done_f, done_b, lambda s, c: (s, c))
    return fwd_tbl, bwd_tbl, fdepth, bdepth, xdepth


def pipeline_1f1b(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                  last_stage_fn: Callable[[jnp.ndarray, Any], jnp.ndarray],
                  stage_params: Any,
                  inputs: jnp.ndarray,
                  targets: Any,
                  axis_name: str = PIPE_AXIS,
                  num_chunks: int = 1,
                  head_params: Any = None,
                  uniform_collectives: bool = False):
    """True 1F1B pipeline: explicit warmup/steady/drain microbatch ordering
    with bounded in-flight activations.  Must run inside shard_map.

    Reference semantics (the 1F1B schedule and its interleaved-virtual-
    stage variant, SURVEY.md §3.2): after a warmup of S−1−s forwards,
    stage s alternates 1F/1B, then drains.  Each SPMD tick carries an F
    slot and a B slot (a combined tick costs what two serial slots would,
    so this only tightens the transient; the steady-state rate is set by
    the 1F1B in-flight cap, exactly as in the reference schedule).  Live activation state is the
    schedule's proven peak in-flight count (S−s inputs per stage for V=1;
    the simulator computes and sizes it exactly), independent of M —
    NOT the M-deep carry stack the autodiff-transposed ring
    (:func:`spmd_pipeline`) keeps — which is the defining property of
    1F1B.  Backward recomputes the stage forward from
    the stashed input (jax.checkpoint-style remat), so a backward tick
    costs ~2 forward units.

    With ``num_chunks=V > 1`` each device owns V non-adjacent virtual
    stages (leaves of ``stage_params`` carry a leading [V] dim; global
    stage v·S+s lives on device s), shrinking the bubble fraction from
    (S−1)/(M+S−1) to ≈(S−1)/(V·M) at the cost of V× activation registers
    and (V−1)·S extra warmup depth — the reference's interleaved
    tradeoff.

    The per-tick schedule is a static table computed by
    :func:`_simulate_1f1b` (M, S, V are trace-time constants), so the
    traced program is a single ``lax.scan`` whose body does masked
    compute (``lax.cond``) + two ring ``ppermute`` hops; a plain-cond
    ``stage_fn`` must therefore be collective-free.

    ``uniform_collectives=True`` (the TP composition): the cond dispatch
    becomes BRANCH-FREE masked compute — every tick on every device runs
    the identical op (and collective) sequence (stage forward, stage vjp,
    loss cell) with the results where-selected by the schedule masks.
    Required when ``stage_fn``/``last_stage_fn`` contain auto-axis (GSPMD
    model) collectives: with per-stage cond predicates only SOME devices
    execute those collectives per tick, the cross-device collective order
    diverges, and the program deadlocks at runtime (observed on the CPU
    backend: 7 devices at the ring ppermute, 1 stuck in a model-pair
    all-reduce).  Cost: bubble ticks compute garbage that is masked out —
    on an SPMD pipeline the tick latency is set by the busiest device
    anyway, so this costs ~no wall-clock; the loss cell does run every
    tick on every device (the cond form runs it once per S·V), which is
    the price of the uniform order.

    Returns ``(mean loss, grads)`` with grads shaped like
    ``stage_params``.

    With ``head_params`` (the real-workload hookup: embedding feeds
    ``inputs``, a parametrized head closes the loss), ``last_stage_fn``
    takes ``(head_params, y, target)`` and the return grows to
    ``(mean loss, grads, head_grads, input_grads)``:

    - ``head_grads``: d(mean loss)/d(head_params), nonzero ONLY on the
      last stage (callers psum over ``axis_name``; other stages
      contribute exact zeros).
    - ``input_grads``: [M, ...] cotangents of ``inputs`` — the first
      stage's per-microbatch dx, which the schedule would otherwise
      discard at the ring seam — nonzero ONLY on stage 0 (psum
      likewise).  Feed them to the embedding's vjp to complete the
      backward; the schedule itself stays a non-differentiable value
      program.
    """
    S = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    V = num_chunks
    M = jax.tree_util.tree_leaves(inputs)[0].shape[0]

    # Uniform chunked form: V=1 gets a singleton chunk dim.
    params = stage_params if V > 1 else jax.tree_util.tree_map(
        lambda p: p[None], stage_params)

    def params_for(c):
        return jax.tree_util.tree_map(
            lambda p: lax.dynamic_index_in_dim(p, c, keepdims=False), params)

    p0 = jax.tree_util.tree_map(lambda p: p[0], params)
    try:
        y_sd = jax.eval_shape(stage_fn, p0, jax.eval_shape(
            lambda a: a[0], inputs))
    except Exception:
        # Best-effort early check only: a TP stage_fn's sharding
        # constraints do not trace under a NESTED eval_shape inside the
        # partially-manual shard_map (the manual-mesh context is lost).
        # The invariant still holds — lax.cond's branch-shape agreement
        # enforces it at trace time, just with a less pointed error.
        y_sd = None
    if y_sd is not None and (y_sd.shape != inputs.shape[1:]
                             or y_sd.dtype != inputs.dtype):
        raise ValueError(
            "stage output must match the per-microbatch input (the ring "
            f"carries one activation shape); got {y_sd.shape}/{y_sd.dtype} "
            f"vs {inputs.shape[1:]}/{inputs.dtype}")
    act_shape, act_dtype = (y_sd.shape, y_sd.dtype) if y_sd is not None \
        else (inputs.shape[1:], inputs.dtype)

    fwd_tbl, bwd_tbl, fdepth, bdepth, xdepth = _simulate_1f1b(M, S, V)
    fwd_tbl = jnp.asarray(fwd_tbl, jnp.int32)
    bwd_tbl = jnp.asarray(bwd_tbl, jnp.int32)

    if head_params is not None:
        # Differentiating w.r.t. a pipe-INVARIANT value makes AD insert the
        # invariance-restoring psum right there — a collective inside a
        # cond whose predicate VARIES per device.  Cast to varying first:
        # the loss-cell grads stay local (masked zeros off the last stage)
        # and the caller performs the one explicit psum at the end.
        head_params = jax.tree_util.tree_map(
            lambda p: lax.pcast(p, axis_name, to="varying")
            if axis_name not in getattr(jax.typeof(p), "vma", frozenset())
            else p, head_params)

    def _idx(stack, i):
        return lax.dynamic_index_in_dim(
            stack, jnp.clip(i, 0, stack.shape[0] - 1), keepdims=False)

    def _idx2(stack, c, k):
        return _idx(_idx(stack, c), k)

    def _upd(stack, val, c):
        return lax.dynamic_update_index_in_dim(stack, val, c, 0)

    def _upd2(stack, val, c, k):
        return _upd(stack, _upd(_idx(stack, c), val, k), c)

    # Activation-valued zeros must carry the SAME varying type as the real
    # compute: over the pipe axis AND over whatever other manual axes the
    # inputs/targets vary on (e.g. 'data' in the BERT integration — batch
    # shards make every activation, dx and loss cell data-varying).
    # Param-GRAD zeros stay pipe-only: stage/head params enter invariant
    # on the other axes, so their cotangents arrive implicitly psum-ed
    # there (safe inside the cond — the action tables vary over pipe only,
    # every other-axis shard takes the same branch).
    def _vma_of(t):
        s = set()
        for leaf in jax.tree_util.tree_leaves(t):
            s |= set(getattr(jax.typeof(leaf), "vma", frozenset()))
        return s

    act_axes = tuple(sorted({axis_name} | _vma_of(inputs) | _vma_of(targets)))

    def _vzeros(shape, dtype):
        return lax.pcast(jnp.zeros(shape, dtype), act_axes, to="varying")

    def _pzeros(shape, dtype):
        return lax.pcast(jnp.zeros(shape, dtype), axis_name, to="varying")

    zeros_act = lambda *lead: _vzeros(lead + act_shape, act_dtype)
    gzero = jax.tree_util.tree_map(
        lambda p: lax.pcast(jnp.zeros(p.shape, jnp.float32), axis_name,
                            to="varying"), params)

    def tick(carry, rows):
        fwd_reg, bwd_reg, xbuf, gacc, lacc, aux = carry
        frow, brow = rows
        af = jnp.take(frow, idx)
        ab = jnp.take(brow, idx)
        do_f, do_b = af >= 0, ab >= 0
        cf, kf = jnp.clip(af, 0) // M, jnp.clip(af, 0) % M
        cb, kb = jnp.clip(ab, 0) // M, jnp.clip(ab, 0) % M

        # The backward's stash read MUST precede the forward's stash write:
        # a combined F+B tick may reuse the same slot (the simulator's
        # depth proof frees a slot at tick start, consume-then-produce).
        xb = _idx2(xbuf, cb, kb % xdepth)

        # ---- forward: consume input or upstream register, stash, compute.
        is_inject = (idx == 0) & (cf == 0)
        x_in = jnp.where(is_inject, _idx(inputs, kf),
                         _idx2(fwd_reg, cf, kf % fdepth))
        if uniform_collectives:
            # branch-free: every device runs the stage (and its model-axis
            # collectives) every tick; the mask selects the result.
            y_real = stage_fn(params_for(cf), x_in).astype(act_dtype)
            if y_real.shape != act_shape:
                # the cond form's branch-shape agreement enforces this;
                # a bare jnp.where would silently BROADCAST a wrong-but-
                # compatible stage output instead of erroring.
                raise ValueError(
                    "stage output must match the per-microbatch input "
                    f"(got {y_real.shape}, need {act_shape})")
            y = jnp.where(do_f, y_real, _vzeros(act_shape, act_dtype))
        else:
            y = lax.cond(do_f,
                         lambda x: stage_fn(params_for(cf),
                                            x).astype(act_dtype),
                         lambda x: _vzeros(act_shape, act_dtype), x_in)
        xbuf = jnp.where(do_f, _upd2(xbuf, x_in, cf, kf % xdepth), xbuf)

        # ---- backward: recompute from stash, pull cotangent, vjp.
        is_last = (idx == S - 1) & (cb == V - 1)
        tgt = jax.tree_util.tree_map(lambda s: _idx(s, kb), targets)

        def _loss_cell_core(yb2, tgt2):
            """ONE definition of the loss-cell math (value_and_grad over
            last_stage_fn + dtype casts), shared by the cond form's
            loss_cell and the branch-free run_bwd_uniform so the two
            dispatch forms can never diverge."""
            if head_params is None:
                lv, dyl = jax.value_and_grad(
                    lambda yy: last_stage_fn(yy, tgt2))(yb2)
                return lv.astype(jnp.float32), dyl.astype(act_dtype), ()
            lv, (dh2, dyl) = jax.value_and_grad(
                lambda hp, yy: last_stage_fn(hp, yy, tgt2),
                argnums=(0, 1))(head_params, yb2)
            return lv.astype(jnp.float32), dyl.astype(act_dtype), dh2

        def run_bwd(opr):
            xb, cot_in, tgt = opr
            pb = params_for(cb)
            yb, vjp = jax.vjp(stage_fn, pb, xb)

            # Only the LAST stage's cell needs the loss backward; nesting
            # the cond spares every other stage the head computation (for
            # a parametrized head that is a full [vocab, hidden]-cotangent
            # backward per tick, thrown away S·V−1 times out of S·V).
            # Legal for the same reason the outer do_b cond is: the
            # predicate varies over the pipe axis only, and the implicit
            # data-axis grad psums inside agree on the branch everywhere.
            def loss_cell(opr2):
                return _loss_cell_core(*opr2)

            def loss_skip(opr2):
                dh0 = () if head_params is None else jax.tree_util.tree_map(
                    lambda p: _pzeros(p.shape, p.dtype), head_params)
                return (_vzeros((), jnp.float32),
                        _vzeros(act_shape, act_dtype), dh0)

            lval, dy_loss, dh = lax.cond(is_last, loss_cell, loss_skip,
                                         (yb, tgt))
            dy = jnp.where(is_last, dy_loss, cot_in)
            dp, dx = vjp(dy.astype(yb.dtype))
            return dp, dx.astype(act_dtype), lval, dh

        def skip_bwd(opr):
            dh = () if head_params is None else jax.tree_util.tree_map(
                lambda p: _pzeros(p.shape, p.dtype), head_params)
            return (jax.tree_util.tree_map(
                        lambda p: _pzeros(p.shape[1:], p.dtype), params),
                    _vzeros(act_shape, act_dtype),
                    _vzeros((), jnp.float32), dh)

        def run_bwd_uniform(opr):
            """Branch-free form of run_bwd: stage vjp AND loss cell run on
            every device every tick (identical collective order — the TP
            requirement), results where-selected.  Garbage compute in
            masked-off ticks never lands: dp is gated by do_b at the gacc
            update, dh by do_b & is_last, dx by the receiver's ab_in >= 0
            mask, and lval is masked here."""
            xb, cot_in, tgt = opr
            pb = params_for(cb)
            yb, vjp = jax.vjp(stage_fn, pb, xb)
            lv, dyl, dh = _loss_cell_core(yb, tgt)
            lval = jnp.where(do_b & is_last, lv,
                             _vzeros((), jnp.float32))
            dy = jnp.where(is_last, dyl, cot_in)
            dp, dx = vjp(dy.astype(yb.dtype))
            return dp, dx.astype(act_dtype), lval, dh

        if uniform_collectives:
            dp, dx, lval, dh = run_bwd_uniform(
                (xb, _idx2(bwd_reg, cb, kb % bdepth), tgt))
        else:
            dp, dx, lval, dh = lax.cond(
                do_b, run_bwd, skip_bwd,
                (xb, _idx2(bwd_reg, cb, kb % bdepth), tgt))
        gacc = jax.tree_util.tree_map(
            lambda a, d: jnp.where(
                do_b, _upd(a, _idx(a, cb) + d.astype(jnp.float32), cb), a),
            gacc, dp)
        lacc = lacc + lval
        if head_params is not None:
            gh, dxa = aux
            # Head grads exist only where the loss cell really ran (last
            # stage, last chunk); input cotangents only where the stage-0
            # backward retired the injected microbatch — exact zeros
            # elsewhere, so a psum over the pipe axis recovers both.
            gh = jax.tree_util.tree_map(
                lambda a, d: jnp.where(do_b & is_last,
                                       a + d.astype(jnp.float32), a),
                gh, dh)
            is_first = (idx == 0) & (cb == 0)
            dxa = jnp.where(
                do_b & is_first,
                lax.dynamic_update_index_in_dim(
                    dxa, dx, jnp.clip(kb, 0, M - 1), 0),
                dxa)
            aux = (gh, dxa)

        # ---- ring exchange (unconditional; receivers mask).
        y_in = send_forward(y, axis_name)
        af_in = send_forward(af, axis_name)
        dx_in = send_backward(dx, axis_name)
        ab_in = send_backward(ab, axis_name)

        cf_in, kf_in = jnp.clip(af_in, 0) // M, jnp.clip(af_in, 0) % M
        c_r = jnp.where(idx == 0, cf_in + 1, cf_in)      # my chunk for it
        fwd_reg = jnp.where(
            (af_in >= 0) & (c_r < V),
            _upd2(fwd_reg, y_in, jnp.clip(c_r, 0, V - 1), kf_in % fdepth),
            fwd_reg)
        cb_in, kb_in = jnp.clip(ab_in, 0) // M, jnp.clip(ab_in, 0) % M
        c_rb = jnp.where(idx == S - 1, cb_in - 1, cb_in)
        bwd_reg = jnp.where(
            (ab_in >= 0) & (c_rb >= 0),
            _upd2(bwd_reg, dx_in, jnp.clip(c_rb, 0, V - 1), kb_in % bdepth),
            bwd_reg)
        return (fwd_reg, bwd_reg, xbuf, gacc, lacc, aux), None

    aux0 = ()
    if head_params is not None:
        aux0 = (jax.tree_util.tree_map(
                    lambda p: lax.pcast(jnp.zeros(p.shape, jnp.float32),
                                        axis_name, to="varying"),
                    head_params),
                _vzeros((M,) + act_shape, act_dtype))
    carry0 = (zeros_act(V, fdepth), zeros_act(V, bdepth),
              zeros_act(V, xdepth), gzero,
              _vzeros((), jnp.float32),       # lacc: loss cells vary like
              aux0)                           # the activations
    (_, _, _, gacc, lacc, aux), _ = lax.scan(
        tick, carry0, (fwd_tbl, bwd_tbl))

    loss = lax.psum(lacc, axis_name) / M
    grads = jax.tree_util.tree_map(
        lambda a, p: (a / M).astype(p.dtype), gacc, params)
    if V == 1:
        grads = jax.tree_util.tree_map(lambda g: g[0], grads)
    if head_params is None:
        return loss, grads
    gh, dxa = aux
    head_grads = jax.tree_util.tree_map(
        lambda a, p: (a / M).astype(p.dtype), gh, head_params)
    input_grads = (dxa.astype(jnp.float32) / M).astype(act_dtype)
    return loss, grads, head_grads, input_grads


def forward_backward_pipelining_without_interleaving(
        stage_fn, last_stage_fn, stage_params, inputs, targets,
        axis_name: str = PIPE_AXIS,
) -> Tuple[jnp.ndarray, Any]:
    """(loss, grads-wrt-stage_params) under the true 1F1B schedule
    (reference entry-point name).  See :func:`pipeline_1f1b`."""
    return pipeline_1f1b(stage_fn, last_stage_fn, stage_params, inputs,
                         targets, axis_name=axis_name, num_chunks=1)


def forward_backward_pipelining_with_interleaving(
        stage_fn, last_stage_fn, stage_params, inputs, targets,
        num_chunks: int, axis_name: str = PIPE_AXIS,
) -> Tuple[jnp.ndarray, Any]:
    """Interleaved-virtual-stage 1F1B (reference entry-point name).
    ``stage_params`` leaves carry a leading [num_chunks] dim; device s owns
    global stages {v·S+s}.  See :func:`pipeline_1f1b`."""
    return pipeline_1f1b(stage_fn, last_stage_fn, stage_params, inputs,
                         targets, axis_name=axis_name,
                         num_chunks=num_chunks)


def get_forward_backward_func(virtual_pipeline_model_parallel_size,
                              pipeline_model_parallel_size):
    """Schedule selector with the reference's exact decision table
    (apex/transformer/pipeline_parallel/schedules/__init__.py):
    pipeline size 1 → :func:`forward_backward_no_pipelining`; a virtual
    (interleaved) size → the interleaved 1F1B variant (callers then pass
    ``num_chunks=virtual_...``); otherwise plain 1F1B.  The returned
    callables keep this package's functional signatures — grads come back
    as values, not module mutations."""
    if pipeline_model_parallel_size == 1:
        return forward_backward_no_pipelining
    if virtual_pipeline_model_parallel_size is not None:
        return forward_backward_pipelining_with_interleaving
    return forward_backward_pipelining_without_interleaving
