"""Pipeline parallelism (reference: apex/transformer/pipeline_parallel/).

Exports the two schedule entry points (SURVEY.md §3.2) and the p2p helpers.
"""

from apex_example_tpu.transformer.pipeline_parallel.schedules import (  # noqa: F401
    forward_backward_no_pipelining,
    get_forward_backward_func,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    pipeline_1f1b,
    spmd_pipeline)
from apex_example_tpu.transformer.pipeline_parallel.p2p_communication import (  # noqa: F401
    recv_backward, recv_forward, send_backward, send_forward)

__all__ = [
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_with_interleaving",
    "forward_backward_pipelining_without_interleaving",
    "get_forward_backward_func",
    "pipeline_1f1b",
    "recv_backward", "recv_forward", "send_backward", "send_forward",
    "spmd_pipeline",
]
