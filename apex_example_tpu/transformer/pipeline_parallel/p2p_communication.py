"""Stage-to-stage activation transfer.

Reference (apex/transformer/pipeline_parallel/p2p_communication.py, SURVEY.md
§3.2): hand-rolled ``torch.distributed`` isend/irecv pairs between adjacent
pipeline ranks, with shape negotiation and separate fwd/bwd channels.

TPU-native restatement: a neighbour shift on the ``pipe`` mesh axis is one
``lax.ppermute``, which XLA lowers to an ICI neighbour exchange; its JAX
transpose is the reverse permutation, so "send_backward" channels are what
autodiff derives from "send_forward" for free.  The wrappers keep the
reference's four names for surface parity; all must run inside shard_map
with ``axis_name`` bound.

The edge semantics differ from isend/irecv in one visible way: a ring
ppermute is collective, so the first stage receives the last stage's payload
(and vice versa).  Schedules mask those wrap-around values instead of not
receiving them — same information flow, collective form.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from apex_example_tpu.parallel.mesh import PIPE_AXIS

__all__ = ["send_forward", "send_backward", "recv_forward", "recv_backward"]


def _ring(axis_name: str, step: int):
    n = lax.axis_size(axis_name)
    return [(i, (i + step) % n) for i in range(n)]


def send_forward(x: jnp.ndarray, axis_name: str = PIPE_AXIS) -> jnp.ndarray:
    """Shift activations one stage downstream (stage i → i+1)."""
    return lax.ppermute(x, axis_name, _ring(axis_name, +1))


def send_backward(g: jnp.ndarray, axis_name: str = PIPE_AXIS) -> jnp.ndarray:
    """Shift gradients one stage upstream (stage i → i−1)."""
    return lax.ppermute(g, axis_name, _ring(axis_name, -1))


# In the collective formulation receive IS the result of the neighbour's
# send; the recv_* names are kept as aliases so reference call sites map 1:1.
recv_forward = send_forward
recv_backward = send_backward
