"""apex.transformer-shaped surface: tensor/sequence/pipeline parallelism.

Reference (SURVEY.md §3.2): ``apex/transformer/`` carries Megatron-derived
tensor parallelism (``tensor_parallel/``: ColumnParallelLinear,
RowParallelLinear, VocabParallelEmbedding, mappings, vocab-parallel
cross-entropy), pipeline parallelism (``pipeline_parallel/``: no-pipelining +
1F1B schedules, p2p_communication), sequence parallelism (a flag on the TP
layers), and ``parallel_state.py`` (TP/PP/DP process-group topology).

TPU-native restatement: the process groups are named axes of one
:class:`jax.sharding.Mesh` (parallel_state), layer parallelism is expressed as
*sharding annotations* that GSPMD lowers to ICI collectives (layers), the
explicit collective mappings exist for shard_map-style manual use (mappings),
and the pipeline schedule is a collective program over the ``pipe`` axis
(pipeline_parallel).
"""

from apex_example_tpu.transformer import parallel_state  # noqa: F401
from apex_example_tpu.transformer import tensor_parallel  # noqa: F401
from apex_example_tpu.transformer import pipeline_parallel  # noqa: F401
from apex_example_tpu.transformer import expert_parallel  # noqa: F401
