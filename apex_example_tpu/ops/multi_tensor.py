"""Multi-tensor ops: scale/unscale, axpby, L2 norms over parameter pytrees.

Reference (csrc/multi_tensor_{scale,axpby,l2norm}_kernel.cu driven by
apex/multi_tensor_apply/; SURVEY.md §2.1): CUDA pays per-launch overhead, so
apex chunks a *list* of tensors into fixed-size blocks and processes the whole
list in a handful of launches.

TPU-native design decision: XLA compiles the entire step into one program, so
launch amortization — the reason multi_tensor_apply exists — is moot.  What
still matters on TPU is HBM traffic: each op should read its operands once.
We therefore keep the *list-wise API* (pytrees in, pytrees out, one finite
flag / one global norm across the whole list) but implement each leaf as a
lane-aligned Pallas kernel (pad to (rows, 128), grid over row blocks), and the
cross-leaf reduction (norms, finite flags) as a tiny XLA combine of per-leaf
partials.  ``interpret=True`` (tests) runs the same kernels on CPU.

The scale kernel doubles as the overflow detector, exactly like
``amp_C.multi_tensor_scale`` whose out-of-band flag the loss scaler reads
(SURVEY.md §4.3) — here the flag is a traced bool, no host sync.
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp

from apex_example_tpu.ops import _config as _cfg
from apex_example_tpu.ops._vma import sds

_LANES = 128
_BLOCK_ROWS = 512  # 512*128*4B = 256 KiB per buffer — comfortably in VMEM


def _interpret() -> bool:
    return _cfg.interpret()


def _use_pallas(*operands) -> bool:
    return _cfg.use_pallas_for(*operands)


def _to_lanes(x: jnp.ndarray) -> Tuple[jnp.ndarray, int]:
    """Flatten a leaf and pad to a (rows, 128) lane-aligned 2-D buffer."""
    flat = x.reshape(-1)
    n = flat.shape[0]
    pad = (-n) % _LANES
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(-1, _LANES), n


def _grid_rows(rows: int) -> Tuple[int, int]:
    """Pick (block_rows, pad_rows): rows pad to a sublane multiple (8), the
    block is the largest power-of-two divisor <= _BLOCK_ROWS so padding never
    exceeds 7 rows (a leaf just over a block boundary must not double its
    HBM traffic)."""
    padded = rows + ((-rows) % 8)
    block = _BLOCK_ROWS
    while padded % block:
        block //= 2
    return block, padded - rows


def _pad_rows(x2d, pad):
    return jnp.pad(x2d, ((0, pad), (0, 0))) if pad else x2d


def _unpad(t, n, like):
    return t.reshape(-1)[:n].reshape(like.shape)


# --------------------------------------------------------------------------
# scale (+ finite check)
# --------------------------------------------------------------------------

def _scale_kernel(x_ref, s_ref, y_ref, bad_ref):
    import jax.experimental.pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _():
        bad_ref[0, 0] = jnp.zeros((), jnp.int32)

    xf = x_ref[:].astype(jnp.float32)
    y = xf * s_ref[0]
    y_ref[:] = y.astype(y_ref.dtype)
    nonfinite = jnp.logical_not(jnp.isfinite(xf)).any()
    bad_ref[0, 0] += nonfinite.astype(jnp.int32)


def _scale_leaf_pallas(x: jnp.ndarray, scale: jnp.ndarray):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    x2d, n = _to_lanes(x)
    rows = x2d.shape[0]
    block, pad_rows = _grid_rows(rows)
    x2d = _pad_rows(x2d, pad_rows)
    grid = x2d.shape[0] // block

    y, bad = pl.pallas_call(
        _scale_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((block, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((block, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            sds(x2d.shape, x.dtype, x2d),
            sds((1, 1), jnp.int32, x2d),
        ],
        interpret=_interpret(),
    )(x2d, scale.astype(jnp.float32).reshape(1))
    return _unpad(y, n, x), bad[0, 0] > 0


def multi_tensor_scale(tree: Any, scale) -> Tuple[Any, jnp.ndarray]:
    """out = in * scale for every leaf; plus an any-nonfinite flag.

    Returns (scaled_tree, all_finite).  Matches amp_C.multi_tensor_scale's
    contract: the flag reflects the *input* values (a nonfinite input is the
    overflow signal, regardless of scale).
    """
    scale = jnp.asarray(scale, jnp.float32)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree, jnp.asarray(True)
    if _use_pallas(scale, *leaves):
        outs, bads = zip(*[_scale_leaf_pallas(l, scale) for l in leaves])
        all_finite = jnp.logical_not(jnp.stack(bads).any())
    else:
        outs = [(l.astype(jnp.float32) * scale).astype(l.dtype)
                for l in leaves]
        all_finite = jnp.stack(
            [jnp.all(jnp.isfinite(l)) for l in leaves]).all()
    return jax.tree_util.tree_unflatten(treedef, outs), all_finite


# --------------------------------------------------------------------------
# axpby
# --------------------------------------------------------------------------

def _axpby_kernel(x_ref, y_ref, ab_ref, o_ref):
    xf = x_ref[:].astype(jnp.float32)
    yf = y_ref[:].astype(jnp.float32)
    o_ref[:] = (ab_ref[0] * xf + ab_ref[1] * yf).astype(o_ref.dtype)


def _axpby_leaf_pallas(x, y, a, b):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    x2d, n = _to_lanes(x)
    y2d, _ = _to_lanes(y)
    rows = x2d.shape[0]
    block, pad_rows = _grid_rows(rows)
    x2d = _pad_rows(x2d, pad_rows)
    y2d = _pad_rows(y2d, pad_rows)
    grid = x2d.shape[0] // block
    ab = jnp.stack([jnp.asarray(a, jnp.float32), jnp.asarray(b, jnp.float32)])

    out = pl.pallas_call(
        _axpby_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((block, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((block, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=sds(x2d.shape, y.dtype, x2d, y2d),
        interpret=_interpret(),
    )(x2d, y2d, ab)
    return _unpad(out, n, x)


def multi_tensor_axpby(a, x_tree: Any, b, y_tree: Any) -> Any:
    """out = a*x + b*y, leafwise (reference: multi_tensor_axpby_kernel.cu)."""
    if _use_pallas(*jax.tree_util.tree_leaves((x_tree, y_tree))):
        return jax.tree_util.tree_map(
            lambda x, y: _axpby_leaf_pallas(x, y, a, b), x_tree, y_tree)
    return jax.tree_util.tree_map(
        lambda x, y: (a * x.astype(jnp.float32)
                      + b * y.astype(jnp.float32)).astype(y.dtype),
        x_tree, y_tree)


# --------------------------------------------------------------------------
# L2 norm (global and per-tensor — LAMB and grad clipping need both)
# --------------------------------------------------------------------------

def _sqsum_kernel(x_ref, acc_ref):
    import jax.experimental.pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _():
        acc_ref[0, 0] = jnp.zeros((), jnp.float32)

    xf = x_ref[:].astype(jnp.float32)
    acc_ref[0, 0] += jnp.sum(xf * xf)


def _sqsum_leaf_pallas(x) -> jnp.ndarray:
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    x2d, _ = _to_lanes(x)
    rows = x2d.shape[0]
    block, pad_rows = _grid_rows(rows)
    x2d = _pad_rows(x2d, pad_rows)
    grid = x2d.shape[0] // block
    acc = pl.pallas_call(
        _sqsum_kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec((block, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        out_shape=sds((1, 1), jnp.float32, x2d),
        interpret=_interpret(),
    )(x2d)
    return acc[0, 0]


def _sqsum_leaf(x) -> jnp.ndarray:
    if _use_pallas(x):
        return _sqsum_leaf_pallas(x)
    xf = x.astype(jnp.float32)
    return jnp.sum(xf * xf)


def sqsum_leaf(x) -> jnp.ndarray:
    """Public per-leaf ||x||² on the kernel path (NovoGrad's per-tensor
    second moment is the squared grad norm)."""
    return _sqsum_leaf(x)


def multi_tensor_l2norm(tree: Any, per_tensor: bool = False):
    """Global L2 norm of all leaves; optionally also per-leaf norms.

    Reference: multi_tensor_l2norm_kernel.cu (per-block partials + final
    reduce); used by grad clipping and LAMB stage 1.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        z = jnp.asarray(0.0, jnp.float32)
        return (z, []) if per_tensor else z
    sq = [_sqsum_leaf(l) for l in leaves]
    total = jnp.sqrt(jnp.stack(sq).sum())
    if per_tensor:
        return total, [jnp.sqrt(s) for s in sq]
    return total


def clip_grad_norm(grads: Any, max_norm: float, eps: float = 1e-6
                   ) -> Tuple[Any, jnp.ndarray]:
    """Global-norm gradient clipping on the multi_tensor_l2norm path
    (reference harness C5 uses clip_grad_norm with FusedLayerNorm models)."""
    total = multi_tensor_l2norm(grads)
    scale = jnp.minimum(1.0, max_norm / (total + eps))
    clipped, _ = multi_tensor_scale(grads, scale)
    return clipped, total


class MultiTensorApply:
    """API-parity shim for apex.multi_tensor_apply.multi_tensor_applier.

    The chunking machinery has no TPU analog (see module docstring); this
    callable simply dispatches to the list-wise ops above so code written
    against the apex pattern keeps a target to call.
    """

    def __init__(self, chunk_size: int = 2048 * 32):
        self.chunk_size = chunk_size  # recorded; chunking is the compiler's job

    def __call__(self, op, *args, **kwargs):
        return op(*args, **kwargs)
