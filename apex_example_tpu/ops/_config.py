"""Kernel-dispatch configuration shared by all ops."""

import contextlib

import jax

INTERPRET = False  # run Pallas kernels in interpreter mode (CPU tests)

# Force the XLA reference implementations even on TPU.  The GSPMD tensor-
# parallel path (engine.make_gspmd_train_step) sets this: pallas_call custom
# calls are opaque to the SPMD partitioner, so inside a plain jit over a
# multi-axis mesh they would be wrapped in gather/replicate instead of
# partitioned — the XLA-native forms partition cleanly.  shard_map paths
# (DP/ZeRO/ring) are unaffected: there the kernels run per-shard by
# construction and keep the pallas dispatch.
FORCE_XLA = False


def set_force_xla(value: bool) -> None:
    global FORCE_XLA
    FORCE_XLA = bool(value)


def get_force_xla() -> bool:
    return FORCE_XLA


@contextlib.contextmanager
def force_xla(value: bool = True):
    """Scoped FORCE_XLA pin, restoring the prior value on exit.

    The flag is process-global and read at TRACE time: anything else that
    first-traces inside the pinned window (another thread, an interleaved
    jit) compiles with this dispatch and caches it — the same caveat as
    train.py's run-long set_force_xla(True), scoped smaller here."""
    global FORCE_XLA
    prev = FORCE_XLA
    FORCE_XLA = bool(value)
    try:
        yield
    finally:
        FORCE_XLA = prev


def interpret() -> bool:
    return INTERPRET


# The kernels (and the interpret-mode vma dance below) target the vma-era
# pallas API (jax >= 0.7).  On older jax the CPU interpreter cannot run
# them; the XLA reference implementations are the correct fallback there.
# TPU dispatch is unaffected either way.
from apex_example_tpu._compat import HAS_VMA as _VMA_TYPING  # noqa: E402
from apex_example_tpu._compat import vma_of as _vma_of  # noqa: E402


def use_pallas() -> bool:
    """Pallas path on TPU (or under the interpreter); XLA reference
    implementations elsewhere."""
    if FORCE_XLA:
        return False
    if INTERPRET:
        return _VMA_TYPING
    return jax.default_backend() in ("tpu", "axon")


def use_pallas_for(*operands) -> bool:
    """Like use_pallas, but under the interpreter (CPU tests) falls back to
    the XLA reference path when an operand varies over a shard_map mesh axis:
    the HLO interpreter evaluates the kernel body with vma-typed values and
    trips on mixed varying/invariant arithmetic.  Real mosaic lowering erases
    vma at the pallas_call boundary, so TPU always keeps the kernel."""
    if FORCE_XLA:
        return False
    if INTERPRET:
        return _VMA_TYPING and not any(_vma_of(x) for x in operands)
    return jax.default_backend() in ("tpu", "axon")
