"""Kernel-dispatch configuration shared by all ops."""

import jax

INTERPRET = False  # run Pallas kernels in interpreter mode (CPU tests)


def interpret() -> bool:
    return INTERPRET


def use_pallas() -> bool:
    """Pallas path on TPU (or under the interpreter); XLA reference
    implementations elsewhere."""
    return INTERPRET or jax.default_backend() in ("tpu", "axon")
