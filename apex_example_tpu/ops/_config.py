"""Kernel-dispatch configuration shared by all ops."""

import jax

INTERPRET = False  # run Pallas kernels in interpreter mode (CPU tests)


def interpret() -> bool:
    return INTERPRET


def use_pallas() -> bool:
    """Pallas path on TPU (or under the interpreter); XLA reference
    implementations elsewhere."""
    return INTERPRET or jax.default_backend() in ("tpu", "axon")


def use_pallas_for(*operands) -> bool:
    """Like use_pallas, but under the interpreter (CPU tests) falls back to
    the XLA reference path when an operand varies over a shard_map mesh axis:
    the HLO interpreter evaluates the kernel body with vma-typed values and
    trips on mixed varying/invariant arithmetic.  Real mosaic lowering erases
    vma at the pallas_call boundary, so TPU always keeps the kernel."""
    if INTERPRET:
        return not any(
            getattr(jax.typeof(x), "vma", frozenset()) for x in operands)
    return jax.default_backend() in ("tpu", "axon")
