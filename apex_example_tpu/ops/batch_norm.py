"""Fused training-mode BatchNorm: one-pass Pallas reduce kernels + custom VJP.

Reference: the syncbn native unit (SURVEY.md §2.1 ledger row "syncbn welford +
psum"; the reference's welford.cu computes local stats in one kernel and the
backward's two gradient sums in another).  Round-1 shipped the XLA composite
form; profiling the C2 step on v5e (tools/trace_top.py) showed the XLA
multi-output reduce fusions that implement BN stats/backward-sums running at
~130-250 GB/s — well under the chip's ~300 GB/s streaming rate — with BN
accounting for ~52% of step time.  This module takes control of exactly those
two passes:

  fwd:  (Σ(x-c), Σ(x-c)²) per channel — one Pallas pass over x
  bwd:  (Σdy, Σdy·x̂)      per channel — one Pallas pass over (x, dy)

while the elementwise normalize/apply (fwd) and dx (bwd) stay in XLA, where
they fuse with the surrounding relu/residual chains.  The custom VJP also
pins the saved residuals to {x (input dtype), mean, inv} so no fp32 copy of
the activation is ever materialized for backward.

Cross-replica (SyncBatchNorm) semantics: the caller passes ``axis_name``;
the per-shard kernel sums are psum-merged *inside* the custom VJP — forward
stats and backward sums each cross the mesh exactly once, matching the
reference's two syncbn allreduces (SURVEY.md §4.4).

Gradient contract: outputs are (y, mean, var).  mean/var exist for running-
stat tracking (a flax variable update, which is not differentiated); their
cotangents are ignored in the backward.  Differentiating through mean/var as
data is NOT supported.  The centering constant ``c`` is a buffer whose true
gradient is identically zero (mean = c + Σ(x-c)/n and var are algebraically
invariant in c), so its returned cotangent is exact.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_example_tpu.ops import _config as _cfg
from apex_example_tpu.ops._vma import sds


def _interpret() -> bool:
    return _cfg.interpret()


def _pick_block(rows: int, channels: int, nbufs: int = 1) -> Optional[int]:
    """Largest row-block that divides ``rows``, is a multiple of 8, and keeps
    each of the kernel's ``nbufs`` streamed (blk, C) buffers ≤ ~1 MiB so the
    double-buffered working set stays well inside the 16 MiB VMEM budget.

    Zero-padding would corrupt the *centered* sums (a padded zero contributes
    (0-c) ≠ 0), so the grid must tile rows exactly; batch×spatial row counts
    (N·H·W with N a multiple of 8) always admit a divisor.
    """
    if rows % 8 != 0:
        return None
    limit = max(8, (1 << 19) // (channels * nbufs))   # 512K elems / bufs
    g = max(-(-rows // limit), 1)                     # ceil: block ≤ limit
    while g <= rows // 8:
        if rows % g == 0 and (rows // g) % 8 == 0:
            return rows // g
        g += 1
    return None


# --------------------------------------------------------------------------
# Pallas kernels
# --------------------------------------------------------------------------

def _stats_kernel(x_ref, c_ref, s_ref, ss_ref):
    """One-pass centered moments: accumulate (Σ(x-c), Σ(x-c)²) in fp32."""
    import jax.experimental.pallas as pl

    xc = x_ref[...].astype(jnp.float32) - c_ref[...]

    @pl.when(pl.program_id(0) == 0)
    def _():
        s_ref[...] = jnp.zeros_like(s_ref)
        ss_ref[...] = jnp.zeros_like(ss_ref)
    s_ref[...] += jnp.sum(xc, axis=0)
    ss_ref[...] += jnp.sum(xc * xc, axis=0)


def _bwd_sums_kernel(x_ref, dy_ref, m_ref, i_ref, s_ref, sx_ref):
    """One-pass backward sums: (Σdy, Σdy·x̂) with x̂ recomputed in-flight."""
    import jax.experimental.pallas as pl

    xhat = (x_ref[...].astype(jnp.float32) - m_ref[...]) * i_ref[...]
    dyf = dy_ref[...].astype(jnp.float32)

    @pl.when(pl.program_id(0) == 0)
    def _():
        s_ref[...] = jnp.zeros_like(s_ref)
        sx_ref[...] = jnp.zeros_like(sx_ref)
    s_ref[...] += jnp.sum(dyf, axis=0)
    sx_ref[...] += jnp.sum(dyf * xhat, axis=0)


def bn_stats(x2: jnp.ndarray, c: jnp.ndarray
             ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-channel centered sums of a (rows, C) view: (Σ(x-c), Σ(x-c)²)."""
    rows, C = x2.shape
    blk = _pick_block(rows, C, nbufs=1)
    if blk is None or not _cfg.use_pallas_for(x2, c):
        xc = x2.astype(jnp.float32) - c
        return jnp.sum(xc, axis=0), jnp.sum(xc * xc, axis=0)

    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    vec = lambda: pl.BlockSpec((C,), lambda i: (0,),
                               memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _stats_kernel,
        grid=(rows // blk,),
        in_specs=[pl.BlockSpec((blk, C), lambda i: (i, 0),
                               memory_space=pltpu.VMEM), vec()],
        out_specs=[vec(), vec()],
        out_shape=[sds((C,), jnp.float32, x2, c)] * 2,
        interpret=_interpret(),
    )(x2, c)


def bn_bwd_sums(x2: jnp.ndarray, dy2: jnp.ndarray, mean: jnp.ndarray,
                inv: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-channel backward sums over (rows, C) views: (Σdy, Σdy·x̂)."""
    rows, C = x2.shape
    blk = _pick_block(rows, C, nbufs=2)
    if blk is None or not _cfg.use_pallas_for(x2, dy2):
        xhat = (x2.astype(jnp.float32) - mean) * inv
        dyf = dy2.astype(jnp.float32)
        return jnp.sum(dyf, axis=0), jnp.sum(dyf * xhat, axis=0)

    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    vec = lambda: pl.BlockSpec((C,), lambda i: (0,),
                               memory_space=pltpu.VMEM)
    mat = lambda: pl.BlockSpec((blk, C), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _bwd_sums_kernel,
        grid=(rows // blk,),
        in_specs=[mat(), mat(), vec(), vec()],
        out_specs=[vec(), vec()],
        out_shape=[sds((C,), jnp.float32, x2, dy2)] * 2,
        interpret=_interpret(),
    )(x2, dy2, mean, inv)


# --------------------------------------------------------------------------
# custom-VJP training-mode batch norm
# --------------------------------------------------------------------------

def _rows(x) -> int:
    n = 1
    for d in x.shape[:-1]:
        n *= d
    return n


def _bn_train_impl(x, scale, bias, c, axis_name, eps, apply_dtype,
                   out_dtype):
    C = x.shape[-1]
    rows = _rows(x)
    s, ss = bn_stats(x.reshape(rows, C), c)
    n = jnp.float32(rows)
    if axis_name is not None:
        s = lax.psum(s, axis_name)
        ss = lax.psum(ss, axis_name)
        n = n * lax.axis_size(axis_name)
    mean_c = s / n
    # Var[x] = E[(x-c)²] − (E[x-c])²; exact for any constant shift c.
    var = jnp.maximum(ss / n - mean_c * mean_c, 0.0)
    mean = c + mean_c
    inv = lax.rsqrt(var + eps)

    md = jnp.dtype(apply_dtype)
    y = ((x.astype(md) - mean.astype(md)) * (inv * scale).astype(md)
         + bias.astype(md)).astype(out_dtype)
    return y, mean, var, inv, n


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def batch_norm_train(x, scale, bias, c, axis_name: Optional[str],
                     eps: float, apply_dtype, out_dtype):
    """Training-mode (Sync)BatchNorm over the last axis of ``x``.

    Args:
      x: (..., C) activations (any float dtype; stats accumulate fp32).
      scale, bias: fp32 (C,) affine parameters.
      c: fp32 (C,) centering constant for the one-pass moments (the running
         mean; any constant is mathematically exact, and tracking the batch
         mean keeps the Σ(x-c)² accumulation cancellation-free).
      axis_name: mesh axis for cross-replica stats, or None.
      eps: variance epsilon.
      apply_dtype: dtype of the normalize-apply arithmetic
         (policy.bn_dtype; fp32 realizes keep_batchnorm_fp32).
      out_dtype: dtype of y (the module's I/O dtype — cast once here so the
         O1 fp32-I/O contract doesn't round-trip through half precision).

    Returns:
      (y, mean, biased_var) — y in out_dtype; mean/var fp32, for running-stat
      updates only (see module docstring for the gradient contract).
    """
    y, mean, var, _, _ = _bn_train_impl(x, scale, bias, c, axis_name, eps,
                                        apply_dtype, out_dtype)
    return y, mean, var


def _bn_train_fwd(x, scale, bias, c, axis_name, eps, apply_dtype, out_dtype):
    y, mean, var, inv, n = _bn_train_impl(x, scale, bias, c, axis_name, eps,
                                          apply_dtype, out_dtype)
    return (y, mean, var), (x, scale, mean, inv, n)


def _bn_train_bwd(axis_name, eps, apply_dtype, out_dtype, saved, cts):
    x, scale, mean, inv, n = saved
    dy, _dmean, _dvar = cts   # mean/var feed undifferentiated buffer updates

    C = x.shape[-1]
    rows = _rows(x)
    sdy, sdyx = bn_bwd_sums(x.reshape(rows, C), dy.reshape(rows, C),
                            mean, inv)
    if axis_name is not None:
        sdy = lax.psum(sdy, axis_name)
        sdyx = lax.psum(sdyx, axis_name)

    dscale = sdyx                       # Σ dy·x̂
    dbias = sdy                         # Σ dy
    # dx = γ·inv·(dy − Σdy/n − x̂·(Σdy·x̂)/n); elementwise — XLA fuses it
    # with the adjacent relu-backward / residual-add chains.
    md = jnp.dtype(apply_dtype)
    g = (scale * inv).astype(md)
    mdy = (sdy / n).astype(md)
    mdyx = (sdyx / n).astype(md)
    xhat = (x.astype(md) - mean.astype(md)) * inv.astype(md)
    dx = (g * (dy.astype(md) - mdy - xhat * mdyx)).astype(x.dtype)
    return dx, dscale, dbias, jnp.zeros_like(mean)


batch_norm_train.defvjp(_bn_train_fwd, _bn_train_bwd)
