"""Pallas TPU kernels + XLA reference implementations.

The TPU-native counterpart of the reference's ``csrc/`` native-extension layer
(SURVEY.md §2.1 ledger).  Each op ships a Pallas kernel (the fused path used
on TPU) and an XLA reference implementation (CPU fallback + test golden).
"""

from apex_example_tpu.ops.attention import (attention_reference,
                                            flash_attention,
                                            flash_attention_with_lse)
from apex_example_tpu.ops.layer_norm import (layer_norm,
                                             layer_norm_reference, rms_norm,
                                             rms_norm_reference)
from apex_example_tpu.ops.multi_tensor import (
    MultiTensorApply, clip_grad_norm, multi_tensor_axpby, multi_tensor_l2norm,
    multi_tensor_scale, sqsum_leaf)
from apex_example_tpu.ops.fused_optim import (
    adagrad_update_leaf, adagrad_update_leaf_reference, adam_update_leaf,
    adam_update_leaf_reference, lamb_stage1_leaf, lamb_stage2_leaf,
    novograd_update_leaf, sgd_update_leaf)
from apex_example_tpu.ops.xentropy import (softmax_cross_entropy,
                                           softmax_cross_entropy_reference)

__all__ = [
    "MultiTensorApply", "adagrad_update_leaf",
    "adagrad_update_leaf_reference", "adam_update_leaf",
    "adam_update_leaf_reference",
    "attention_reference", "flash_attention", "flash_attention_with_lse",
    "softmax_cross_entropy", "softmax_cross_entropy_reference",
    "clip_grad_norm", "lamb_stage1_leaf", "lamb_stage2_leaf", "layer_norm",
    "layer_norm_reference", "multi_tensor_axpby", "multi_tensor_l2norm",
    "multi_tensor_scale", "novograd_update_leaf", "rms_norm",
    "rms_norm_reference", "sgd_update_leaf", "sqsum_leaf",
]


def set_interpret_mode(enable: bool) -> None:
    """Run all Pallas kernels in interpreter mode (CPU tests)."""
    from apex_example_tpu.ops import _config
    _config.INTERPRET = bool(enable)
