"""ShapeDtypeStruct construction that survives vma-checked shard_map.

Inside ``shard_map(..., check_vma=True)`` (the default, and required for
correct psum transposes — see engine.py), ``pallas_call`` demands that output
avals declare how they vary over mesh axes.  Kernel outputs vary exactly as
the union of their operands' variances, so every pallas_call in this package
builds its ``out_shape`` through :func:`sds`.
"""

from __future__ import annotations

import jax

def sds(shape, dtype, *operands) -> jax.ShapeDtypeStruct:
    vma = frozenset()
    for r in operands:
        vma = vma | getattr(jax.typeof(r), "vma", frozenset())
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:  # older jax without vma kwarg
        return jax.ShapeDtypeStruct(shape, dtype)
