"""ShapeDtypeStruct construction that survives vma-checked shard_map.

Inside ``shard_map(..., check_vma=True)`` (the default, and required for
correct psum transposes — see engine.py), ``pallas_call`` demands that output
avals declare how they vary over mesh axes.  Kernel outputs vary exactly as
the union of their operands' variances, so every pallas_call in this package
builds its ``out_shape`` through :func:`sds`.
"""

from __future__ import annotations

import jax

from apex_example_tpu._compat import vma_of


def sds(shape, dtype, *operands) -> jax.ShapeDtypeStruct:
    vma = frozenset()
    for r in operands:
        vma = vma | vma_of(r)
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:  # older jax without vma kwarg
        return jax.ShapeDtypeStruct(shape, dtype)


def align_param_grad(g, param):
    """psum a custom-VJP *parameter* cotangent over mesh axes the parameter
    is invariant in but the computed grad varies in.

    For regular primitives jax's vma-aware AD inserts exactly this psum when
    transposing the implicit broadcast of a replicated parameter; a
    custom_vjp backward bypasses that machinery, so its parameter grads
    would stay shard-varying — which both breaks vma typing under composed
    transforms (scan-over-backward in the pipeline schedules) and differs
    from what every non-custom op produces.  No-op outside shard_map or when
    the variances already agree.  Downstream reductions stay correct:
    allreduce_grads infers per-leaf from the aval whether a grad is already
    summed.
    """
    from jax import lax
    extra = tuple(sorted(vma_of(g) - vma_of(param)))
    return lax.psum(g, extra) if extra else g
