"""Fused optimizer update kernels (Adam/AdamW, LAMB stages, SGD+momentum,
NovoGrad).

Reference (csrc/multi_tensor_adam.cu, multi_tensor_lamb.cu with
lamb_stage_1/lamb_stage_2, multi_tensor_sgd_kernel.cu,
multi_tensor_novograd.cu; SURVEY.md §2.1): one CUDA launch updates chunks of
(p, g, m, v) in place for the whole param list.

TPU-native design: the payoff of fusion here is reading p/g/m/v from HBM once
and writing p'/m'/v' once — a Pallas kernel per leaf does exactly that, with
``input_output_aliases`` donating p/m/v so XLA updates in place.  Hyper-
parameters and bias corrections arrive as an SMEM scalar vector, so one
compiled kernel serves every step (step count enters only through the scalar
values, keeping the trace static).

LAMB keeps the reference's two-stage shape: stage 1 produces the Adam-style
update plus per-tensor squared norms of param and update (the per-block
partial-norms trick collapses into the same kernel); the per-tensor trust
ratios are O(#tensors) scalar work done in XLA; stage 2 is a scaled apply.

XLA reference implementations live alongside (``*_reference``) and serve as
CPU fallback and as the golden in kernel tests (which additionally compare
against torch.optim on identical data, SURVEY.md §5).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from apex_example_tpu.ops import _config as _cfg
from apex_example_tpu.ops._vma import sds
from apex_example_tpu.ops.multi_tensor import (_LANES, _grid_rows,
                                               _pad_rows, _to_lanes,
                                               _unpad)


def _interpret() -> bool:
    return _cfg.interpret()


def _use_pallas(*operands) -> bool:
    return _cfg.use_pallas_for(*operands)


# --------------------------------------------------------------------------
# Adam / AdamW
# --------------------------------------------------------------------------

def _adam_kernel(p_ref, g_ref, m_ref, v_ref, s_ref,
                 po_ref, mo_ref, vo_ref, *, adam_w):
    lr, b1, b2, eps, wd, c1, c2 = (s_ref[i] for i in range(7))
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    m = m_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)

    if not adam_w:            # classic Adam: L2 folded into the gradient
        g = g + wd * p
    m = b1 * m + (1.0 - b1) * g
    v = b2 * v + (1.0 - b2) * g * g
    update = (m * c1) / (jnp.sqrt(v * c2) + eps)
    if adam_w:                # AdamW: decoupled decay on the param
        update = update + wd * p
    p = p - lr * update

    po_ref[:] = p.astype(po_ref.dtype)
    mo_ref[:] = m.astype(mo_ref.dtype)
    vo_ref[:] = v.astype(vo_ref.dtype)


def adam_update_leaf(p, g, m, v, *, lr, beta1, beta2, eps, weight_decay,
                     bias_c1, bias_c2, adam_w_mode: bool = True):
    """One fused Adam step for one leaf.  Scalars may be traced values."""
    if not _use_pallas(p, g, m, v):
        return adam_update_leaf_reference(
            p, g, m, v, lr=lr, beta1=beta1, beta2=beta2, eps=eps,
            weight_decay=weight_decay, bias_c1=bias_c1, bias_c2=bias_c2,
            adam_w_mode=adam_w_mode)

    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    p2, n = _to_lanes(p)
    g2, _ = _to_lanes(g)
    m2, _ = _to_lanes(m)
    v2, _ = _to_lanes(v)
    rows = p2.shape[0]
    block, pad = _grid_rows(rows)
    p2, g2, m2, v2 = (_pad_rows(t, pad) for t in (p2, g2, m2, v2))
    grid = p2.shape[0] // block
    scal = jnp.stack([jnp.asarray(s, jnp.float32) for s in
                      (lr, beta1, beta2, eps, weight_decay,
                       bias_c1, bias_c2)])

    bspec = lambda: pl.BlockSpec((block, _LANES), lambda i: (i, 0),
                                 memory_space=pltpu.VMEM)
    po, mo, vo = pl.pallas_call(
        functools.partial(_adam_kernel, adam_w=adam_w_mode),
        grid=(grid,),
        in_specs=[bspec(), bspec(), bspec(), bspec(),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[bspec(), bspec(), bspec()],
        out_shape=[sds(p2.shape, p.dtype, p2, g2, m2, v2),
                   sds(p2.shape, m.dtype, p2, g2, m2, v2),
                   sds(p2.shape, v.dtype, p2, g2, m2, v2)],
        input_output_aliases={0: 0, 2: 1, 3: 2},
        interpret=_interpret(),
    )(p2, g2, m2, v2, scal)

    return _unpad(po, n, p), _unpad(mo, n, m), _unpad(vo, n, v)


def adam_update_leaf_reference(p, g, m, v, *, lr, beta1, beta2, eps,
                               weight_decay, bias_c1, bias_c2,
                               adam_w_mode: bool = True):
    pf, gf = p.astype(jnp.float32), g.astype(jnp.float32)
    mf, vf = m.astype(jnp.float32), v.astype(jnp.float32)
    if not adam_w_mode:
        gf = gf + weight_decay * pf
    mf = beta1 * mf + (1.0 - beta1) * gf
    vf = beta2 * vf + (1.0 - beta2) * gf * gf
    upd = (mf * bias_c1) / (jnp.sqrt(vf * bias_c2) + eps)
    if adam_w_mode:
        upd = upd + weight_decay * pf
    pf = pf - lr * upd
    return pf.astype(p.dtype), mf.astype(m.dtype), vf.astype(v.dtype)


# --------------------------------------------------------------------------
# LAMB stage 1: Adam-style update + per-tensor sq-norms of param and update
# --------------------------------------------------------------------------

def _lamb1_kernel(p_ref, g_ref, m_ref, v_ref, s_ref,
                  u_ref, mo_ref, vo_ref, norms_ref, *, nrows):
    import jax.experimental.pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _():
        norms_ref[0] = jnp.zeros((), jnp.float32)
        norms_ref[1] = jnp.zeros((), jnp.float32)

    b1, b2, eps, wd, c1, c2, gscale = (s_ref[i] for i in range(7))
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32) * gscale   # global grad-norm clip factor
    m = m_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)

    m = b1 * m + (1.0 - b1) * g
    v = b2 * v + (1.0 - b2) * g * g
    u = (m * c1) / (jnp.sqrt(v * c2) + eps) + wd * p

    # Padded tail rows hold zeros, so they add nothing to the norms.  Rows
    # beyond the true element count n were zero-padded in _to_lanes.
    del nrows
    norms_ref[0] += jnp.sum(p * p)
    norms_ref[1] += jnp.sum(u * u)

    u_ref[:] = u
    mo_ref[:] = m.astype(mo_ref.dtype)
    vo_ref[:] = v.astype(vo_ref.dtype)


def lamb_stage1_leaf(p, g, m, v, *, beta1, beta2, eps, weight_decay,
                     bias_c1, bias_c2, grad_scale=1.0):
    """Returns (update, m', v', ||p||², ||update||²) for one leaf."""
    if not _use_pallas(p, g, m, v):
        pf, gf = p.astype(jnp.float32), g.astype(jnp.float32) * grad_scale
        mf, vf = m.astype(jnp.float32), v.astype(jnp.float32)
        mf = beta1 * mf + (1.0 - beta1) * gf
        vf = beta2 * vf + (1.0 - beta2) * gf * gf
        u = (mf * bias_c1) / (jnp.sqrt(vf * bias_c2) + eps) + weight_decay * pf
        return (u, mf.astype(m.dtype), vf.astype(v.dtype),
                jnp.sum(pf * pf), jnp.sum(u * u))

    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    p2, n = _to_lanes(p)
    g2, _ = _to_lanes(g)
    m2, _ = _to_lanes(m)
    v2, _ = _to_lanes(v)
    rows = p2.shape[0]
    block, pad = _grid_rows(rows)
    p2, g2, m2, v2 = (_pad_rows(t, pad) for t in (p2, g2, m2, v2))
    grid = p2.shape[0] // block
    scal = jnp.stack([jnp.asarray(s, jnp.float32) for s in
                      (beta1, beta2, eps, weight_decay, bias_c1, bias_c2,
                       grad_scale)])

    bspec = lambda: pl.BlockSpec((block, _LANES), lambda i: (i, 0),
                                 memory_space=pltpu.VMEM)
    u, mo, vo, norms = pl.pallas_call(
        functools.partial(_lamb1_kernel, nrows=rows),
        grid=(grid,),
        in_specs=[bspec(), bspec(), bspec(), bspec(),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[bspec(), bspec(), bspec(),
                   pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_shape=[sds(p2.shape, jnp.float32, p2, g2, m2, v2),
                   sds(p2.shape, m.dtype, p2, g2, m2, v2),
                   sds(p2.shape, v.dtype, p2, g2, m2, v2),
                   sds((2,), jnp.float32, p2, g2, m2, v2)],
        input_output_aliases={2: 1, 3: 2},
        interpret=_interpret(),
    )(p2, g2, m2, v2, scal)

    return (_unpad(u, n, p), _unpad(mo, n, m), _unpad(vo, n, v),
            norms[0], norms[1])


# --------------------------------------------------------------------------
# LAMB stage 2: p -= lr * trust_ratio * update  (an axpby specialization)
# --------------------------------------------------------------------------

def _lamb2_kernel(p_ref, u_ref, s_ref, po_ref):
    po_ref[:] = (p_ref[:].astype(jnp.float32)
                 - s_ref[0] * u_ref[:].astype(jnp.float32)
                 ).astype(po_ref.dtype)


def lamb_stage2_leaf(p, update, scaled_lr):
    """p' = p - scaled_lr * update (scaled_lr = lr * trust_ratio, traced)."""
    if not _use_pallas(p, update):
        return (p.astype(jnp.float32)
                - scaled_lr * update.astype(jnp.float32)).astype(p.dtype)

    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    p2, n = _to_lanes(p)
    u2, _ = _to_lanes(update)
    rows = p2.shape[0]
    block, pad = _grid_rows(rows)
    p2, u2 = _pad_rows(p2, pad), _pad_rows(u2, pad)
    grid = p2.shape[0] // block
    bspec = lambda: pl.BlockSpec((block, _LANES), lambda i: (i, 0),
                                 memory_space=pltpu.VMEM)
    po = pl.pallas_call(
        _lamb2_kernel,
        grid=(grid,),
        in_specs=[bspec(), bspec(),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=bspec(),
        out_shape=sds(p2.shape, p.dtype, p2, u2),
        input_output_aliases={0: 0},
        interpret=_interpret(),
    )(p2, u2, jnp.asarray(scaled_lr, jnp.float32).reshape(1))
    return _unpad(po, n, p)


# --------------------------------------------------------------------------
# SGD (+ momentum, nesterov)
# --------------------------------------------------------------------------

def _sgd_kernel(p_ref, g_ref, b_ref, s_ref, po_ref, bo_ref, *, nesterov,
                first_step):
    lr, mom, wd, damp = (s_ref[i] for i in range(4))
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    g = g + wd * p
    if first_step:
        buf = g          # torch: first momentum buffer is the (decayed) grad
    else:
        buf = mom * b_ref[:].astype(jnp.float32) + (1.0 - damp) * g
    step_dir = (g + mom * buf) if nesterov else buf
    po_ref[:] = (p - lr * step_dir).astype(po_ref.dtype)
    bo_ref[:] = buf.astype(bo_ref.dtype)


def _novograd_kernel(p_ref, g_ref, m_ref, s_ref, po_ref, mo_ref):
    inv_denom, wd, b1, ga, lr_c1 = (s_ref[i] for i in range(5))
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    m = m_ref[:].astype(jnp.float32)
    g_hat = g * inv_denom + wd * p       # normalized grad + L2 (reg outside)
    m = b1 * m + ga * g_hat
    po_ref[:] = (p - lr_c1 * m).astype(po_ref.dtype)
    mo_ref[:] = m.astype(mo_ref.dtype)


def novograd_update_leaf(p, g, m, *, inv_denom, lr_c1, beta1, weight_decay,
                         grad_avg_coeff):
    """Fused NovoGrad apply for one leaf, given the per-tensor normalization
    scalar ``inv_denom`` = 1/(sqrt(v̂)+eps) (reference:
    multi_tensor_novograd.cu — the per-tensor second moment is the squared
    grad L2-norm, so it is scalar work outside the elementwise kernel).

    g_hat = g*inv_denom + wd*p;  m' = b1*m + ga*g_hat;  p' = p − lr_c1*m'
    (lr_c1 folds the bias correction 1/(1−b1^t) into the learning rate).
    """
    if not _use_pallas(p, g, m):
        pf, gf = p.astype(jnp.float32), g.astype(jnp.float32)
        mf = m.astype(jnp.float32)
        g_hat = gf * inv_denom + weight_decay * pf
        mf = beta1 * mf + grad_avg_coeff * g_hat
        return (pf - lr_c1 * mf).astype(p.dtype), mf.astype(m.dtype)

    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    p2, n = _to_lanes(p)
    g2, _ = _to_lanes(g)
    m2, _ = _to_lanes(m)
    rows = p2.shape[0]
    block, pad = _grid_rows(rows)
    p2, g2, m2 = (_pad_rows(t, pad) for t in (p2, g2, m2))
    grid = p2.shape[0] // block
    scal = jnp.stack([jnp.asarray(s, jnp.float32) for s in
                      (inv_denom, weight_decay, beta1, grad_avg_coeff,
                       lr_c1)])
    bspec = lambda: pl.BlockSpec((block, _LANES), lambda i: (i, 0),
                                 memory_space=pltpu.VMEM)
    po, mo = pl.pallas_call(
        _novograd_kernel,
        grid=(grid,),
        in_specs=[bspec(), bspec(), bspec(),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[bspec(), bspec()],
        out_shape=[sds(p2.shape, p.dtype, p2, g2, m2),
                   sds(p2.shape, m.dtype, p2, g2, m2)],
        input_output_aliases={0: 0, 2: 1},
        interpret=_interpret(),
    )(p2, g2, m2, scal)
    return _unpad(po, n, p), _unpad(mo, n, m)


def sgd_update_leaf(p, g, buf, *, lr, momentum, weight_decay, dampening=0.0,
                    nesterov=False, first_step=False):
    """Fused momentum-SGD step (reference: multi_tensor_sgd_kernel.cu)."""
    if not _use_pallas(p, g, buf):
        pf, gf = p.astype(jnp.float32), g.astype(jnp.float32)
        gf = gf + weight_decay * pf
        if first_step:
            nb = gf          # torch: first buffer is the (decayed) grad
        else:
            nb = momentum * buf.astype(jnp.float32) + (1.0 - dampening) * gf
        step_dir = (gf + momentum * nb) if nesterov else nb
        return (pf - lr * step_dir).astype(p.dtype), nb.astype(buf.dtype)

    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    p2, n = _to_lanes(p)
    g2, _ = _to_lanes(g)
    b2, _ = _to_lanes(buf)
    rows = p2.shape[0]
    block, pad = _grid_rows(rows)
    p2, g2, b2 = (_pad_rows(t, pad) for t in (p2, g2, b2))
    grid = p2.shape[0] // block
    scal = jnp.stack([jnp.asarray(s, jnp.float32) for s in
                      (lr, momentum, weight_decay, dampening)])
    bspec = lambda: pl.BlockSpec((block, _LANES), lambda i: (i, 0),
                                 memory_space=pltpu.VMEM)
    po, bo = pl.pallas_call(
        functools.partial(_sgd_kernel, nesterov=nesterov,
                          first_step=first_step),
        grid=(grid,),
        in_specs=[bspec(), bspec(), bspec(),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[bspec(), bspec()],
        out_shape=[sds(p2.shape, p.dtype, p2, g2, b2),
                   sds(p2.shape, buf.dtype, p2, g2, b2)],
        input_output_aliases={0: 0, 2: 1},
        interpret=_interpret(),
    )(p2, g2, b2, scal)
    return _unpad(po, n, p), _unpad(bo, n, buf)


# --------------------------------------------------------------------------
# Adagrad (reference: apex/optimizers/fused_adagrad.py backed by
# multi_tensor_adagrad.cu): h += g²; p -= lr·g/(√h + eps).  Weight decay is
# L2-into-the-gradient by default, decoupled under adagrad_w_mode — the same
# switch FusedAdam exposes.
# --------------------------------------------------------------------------

def _adagrad_kernel(p_ref, g_ref, h_ref, s_ref, po_ref, ho_ref, *,
                    adagrad_w):
    lr, eps, wd = (s_ref[i] for i in range(3))
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    h = h_ref[:].astype(jnp.float32)
    if not adagrad_w:
        g = g + wd * p
    h = h + g * g
    upd = g / (jnp.sqrt(h) + eps)
    if adagrad_w:
        upd = upd + wd * p
    po_ref[:] = (p - lr * upd).astype(po_ref.dtype)
    ho_ref[:] = h.astype(ho_ref.dtype)


def adagrad_update_leaf(p, g, h, *, lr, eps, weight_decay,
                        adagrad_w_mode: bool = False):
    """One fused Adagrad step for one leaf.  Scalars may be traced."""
    if not _use_pallas(p, g, h):
        return adagrad_update_leaf_reference(
            p, g, h, lr=lr, eps=eps, weight_decay=weight_decay,
            adagrad_w_mode=adagrad_w_mode)

    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    p2, n = _to_lanes(p)
    g2, _ = _to_lanes(g)
    h2, _ = _to_lanes(h)
    rows = p2.shape[0]
    block, pad = _grid_rows(rows)
    p2, g2, h2 = (_pad_rows(t, pad) for t in (p2, g2, h2))
    grid = p2.shape[0] // block
    scal = jnp.stack([jnp.asarray(s, jnp.float32) for s in
                      (lr, eps, weight_decay)])
    bspec = lambda: pl.BlockSpec((block, _LANES), lambda i: (i, 0),
                                 memory_space=pltpu.VMEM)
    po, ho = pl.pallas_call(
        functools.partial(_adagrad_kernel, adagrad_w=adagrad_w_mode),
        grid=(grid,),
        in_specs=[bspec(), bspec(), bspec(),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[bspec(), bspec()],
        out_shape=[sds(p2.shape, p.dtype, p2, g2, h2),
                   sds(p2.shape, h.dtype, p2, g2, h2)],
        input_output_aliases={0: 0, 2: 1},
        interpret=_interpret(),
    )(p2, g2, h2, scal)
    return _unpad(po, n, p), _unpad(ho, n, h)


def adagrad_update_leaf_reference(p, g, h, *, lr, eps, weight_decay,
                                  adagrad_w_mode: bool = False):
    pf, gf = p.astype(jnp.float32), g.astype(jnp.float32)
    hf = h.astype(jnp.float32)
    if not adagrad_w_mode:
        gf = gf + weight_decay * pf
    hf = hf + gf * gf
    upd = gf / (jnp.sqrt(hf) + eps)
    if adagrad_w_mode:
        upd = upd + weight_decay * pf
    return (pf - lr * upd).astype(p.dtype), hf.astype(h.dtype)
