"""Fused softmax cross-entropy with label smoothing.

Reference: apex.contrib.xentropy (``SoftmaxCrossEntropyLoss``, backed by
apex/contrib/csrc/xentropy — SURVEY.md §2.1 contrib row): one CUDA kernel
computes the loss without materializing log-softmax, and the backward
rebuilds ``softmax − target`` on the fly.

TPU-native design: a ``custom_vjp`` over the logsumexp form.  The forward
saves only ``(logits, labels, lse)`` — logits are an input the caller
already holds, and lse is O(tokens) — and the backward REMATERIALIZES the
(tokens, V) probability tensor as ``exp(logits − lse)`` instead of storing
it.  Under plain autodiff the residual set includes an O(tokens·V) tensor
(log-softmax or probs); at BERT scale (B·S·V fp32 logits are ~GBs) dropping
that residual is the entire point of the contrib kernel, and XLA fuses the
rematerialized exp into the backward's subtract.  No Pallas kernel is
needed: both passes are single fused elementwise+reduce sweeps, which XLA
already emits optimally (the same rely-on-XLA stance as fused_dense,
SURVEY.md §2.1).

Smoothing semantics match torch/apex: the target distribution is
``(1−ε)·δ_y + ε/V`` uniformly over the V classes, i.e.
``loss = lse − (1−ε)·z_y − (ε/V)·Σ_j z_j``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["softmax_cross_entropy", "softmax_cross_entropy_reference"]


def softmax_cross_entropy_reference(logits, labels, smoothing: float = 0.0):
    """Plain-autodiff form (test golden): per-example loss, fp32."""
    lf = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(lf, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    if smoothing == 0.0:
        return nll
    return (1.0 - smoothing) * nll - smoothing * jnp.mean(logp, axis=-1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def softmax_cross_entropy(logits, labels, smoothing: float = 0.0):
    """Per-example softmax CE: logits (..., V) any float dtype, labels
    (...,) int; returns fp32 losses of shape (...).  The backward never
    stores the (..., V) probability tensor (see module docstring)."""
    loss, _ = _xent_fwd(logits, labels, smoothing)
    return loss


def _xent_fwd(logits, labels, smoothing):
    lf = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(lf, axis=-1)
    z_y = jnp.take_along_axis(lf, labels[..., None], axis=-1)[..., 0]
    loss = lse - z_y
    if smoothing:
        v = logits.shape[-1]
        # lse − (1−ε)z_y − (ε/V)Σz  ==  (1−ε)(lse − z_y) + ε(lse − mean z)
        loss = loss + smoothing * (z_y - jnp.mean(lf, axis=-1))
    return loss, lse


def _xent_fwd_vjp(logits, labels, smoothing):
    loss, lse = _xent_fwd(logits, labels, smoothing)
    return loss, (logits, labels, lse)


def _xent_bwd_vjp(smoothing, res, dloss):
    logits, labels, lse = res
    lf = logits.astype(jnp.float32)
    p = jnp.exp(lf - lse[..., None])          # rematerialized, fused by XLA
    v = logits.shape[-1]
    onehot = jax.nn.one_hot(labels, v, dtype=jnp.float32)
    target = (1.0 - smoothing) * onehot + smoothing / v
    dlogits = (p - target) * dloss[..., None].astype(jnp.float32)
    return dlogits.astype(logits.dtype), None


softmax_cross_entropy.defvjp(_xent_fwd_vjp, _xent_bwd_vjp)
