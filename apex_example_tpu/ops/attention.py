"""Fused multi-head attention (flash attention): Pallas TPU kernels + XLA
reference.

Reference: apex ships fused attention as a contrib CUDA extension
(apex/contrib/csrc/fmha — SURVEY.md §2.1 contrib row) used by its BERT
recipes; the in-tree models otherwise materialize the full (Sq, Sk) score
matrix.  This module is the TPU-native equivalent and the long-context
workhorse the task brief asks for: blockwise attention whose score matrix
never leaves VMEM, so HBM traffic is O(S·D) instead of O(S²).

TPU-native design
-----------------
One forward Pallas kernel gridded ``(batch*heads, q_blocks, kv_blocks)``
with the kv dimension innermost (TPU grids run sequentially, so the running
online-softmax state lives in VMEM scratch across kv steps):

    m    running row max            (block_q, 1)  fp32
    l    running row sum of exp     (block_q, 1)  fp32
    acc  running unnormalized P·V   (block_q, D)  fp32

Each step computes ``S = QK^T·scale (+bias) (+causal mask)`` on the MXU with
fp32 accumulation, rescales (m, l, acc) by ``exp(m_old - m_new)``, and at the
last kv step writes ``O = acc / l`` plus the row logsumexp (saved for the
backward).  The backward follows the standard two-kernel flash decomposition:
a dK/dV kernel gridded over kv blocks (q innermost, accumulating in scratch)
and a dQ kernel gridded over q blocks (kv innermost), both recomputing
``P = exp(S - lse)`` from the saved logsumexp instead of storing it —
rematerialization trades MXU FLOPs for the O(S²) HBM tensor, the same trade
the LayerNorm kernel makes for x̂.

Numerics: logits and softmax are always fp32 (the amp "blacklist" contract —
SURVEY.md §3.1; model code keeps a naive path for O3's half-softmax).  The
probability matrix is cast back to the input dtype for the P·V / P^T·dO
matmuls so the MXU runs bf16 with fp32 accumulation, matching the XLA
reference path below, which is also the CPU fallback and the test golden.

Supported bias: an additive per-key bias of shape (B, Sk) — the key-padding
mask form BERT uses (already clamped to a finite "minus infinity" by the
model).  The bias is a constant mask, not a learned tensor: its VJP is zero.
Rows whose every key is masked produce an arbitrary convex combination of
values (the reference's softmax over all -1e9 logits does the same).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_example_tpu.ops import _config as _cfg
from apex_example_tpu.ops._vma import sds

# Finite stand-in for -inf: exp(_MASK - anything_reasonable) == 0 in fp32,
# while (_MASK - _MASK) == 0 keeps fully-masked prefixes NaN-free (they are
# then exactly cancelled by the exp(m_old - m_new) rescale once a live block
# arrives).
_MASK = -0.7 * float(jnp.finfo(jnp.float32).max)


def _dot_f32(a, b, *, trans_a=False, trans_b=False):
    """MXU matmul with fp32 accumulation regardless of operand dtype."""
    ca = ((0,) if trans_a else (1,), (1,) if trans_b else (0,))
    return lax.dot_general(a, b, (ca, ((), ())),
                           preferred_element_type=jnp.float32)


# --------------------------------------------------------------------------
# XLA reference path (CPU fallback + kernel-test golden).
# --------------------------------------------------------------------------

def _scores_reference(q, k, bias, causal, scale):
    """fp32 (B, H, Sq, Sk) scores: scaled QK^T, bias, causal mask — the one
    place the reference-path score semantics live (the Pallas counterpart is
    _scores)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias[:, None, None, :].astype(jnp.float32)
    if causal:
        # Bottom-right aligned (the prefix-cache convention): when Sq < Sk
        # the queries are the LAST Sq positions, so query i sees keys
        # 0..(Sk-Sq)+i.  For Sq == Sk this is the ordinary triangular mask.
        sq, sk = q.shape[1], k.shape[1]
        mask = (lax.broadcasted_iota(jnp.int32, (sq, sk), 0) + (sk - sq)
                >= lax.broadcasted_iota(jnp.int32, (sq, sk), 1))
        s = jnp.where(mask, s, _MASK)
    return s


def attention_reference(q, k, v, bias=None, causal=False,
                        scale: Optional[float] = None):
    """Naive attention.  q: (B, Sq, H, D); k/v: (B, Sk, H, D);
    bias: (B, Sk) additive, already finite; returns (B, Sq, H, D)."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    s = _scores_reference(q, k, bias, causal, scale)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def _reference_pair(q, k, v, bias, causal, scale):
    """attention_reference's output plus its (B, H, Sq) row logsumexp, both
    derived from ONE score tensor (keeps out and lse mutually consistent on
    the fallback path — the ring combine weights depend on that)."""
    s = _scores_reference(q, k, bias, causal, scale)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None]).astype(q.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v,
                     preferred_element_type=jnp.float32).astype(q.dtype)
    return out, lse


# --------------------------------------------------------------------------
# Pallas kernels.  All operate on (BH, S, D) with B*H folded into the grid.
# --------------------------------------------------------------------------

def _when_live(i, j, *, causal, bq, bk, off):
    """Decorator: run the kernel body only when causal masking leaves the
    (q block i, kv block j) pair any live entries — i.e. the kv block starts
    at or before the q block's last visible key.  Skipping dead pairs saves
    ~half the causal grid's MXU work (init/write steps stay unguarded).
    Non-causal attention has no dead pairs; the body runs unconditionally."""
    if not causal:
        return lambda body: body()
    return pl.when(j * bk <= i * bq + off + bq - 1)


def _scores(q, k, bias_ref, i, j, *, scale, causal, bq, bk, off):
    """fp32 (bq, bk) logits for q block i vs kv block j: scale, bias, mask.

    ``off`` = Sk - Sq implements the bottom-right-aligned causal convention
    (see attention_reference)."""
    s = _dot_f32(q, k, trans_b=True) * scale
    if bias_ref is not None:
        s = s + bias_ref[0, 0][None, :].astype(jnp.float32)
    if causal:
        row = i * bq + off + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        col = j * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(row >= col, s, _MASK)
    return s


def _fwd_kernel(*refs, scale, causal, bq, bk, nk, has_bias, off):
    if has_bias:
        q_ref, k_ref, v_ref, b_ref, o_ref, lse_ref, acc, m, l = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m, l = refs
        b_ref = None
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _():
        m[:] = jnp.full_like(m, _MASK)
        l[:] = jnp.zeros_like(l)
        acc[:] = jnp.zeros_like(acc)

    @_when_live(i, j, causal=causal, bq=bq, bk=bk, off=off)
    def _():
        s = _scores(q_ref[0], k_ref[0], b_ref, i, j,
                    scale=scale, causal=causal, bq=bq, bk=bk,
                    off=off)
        m_new = jnp.maximum(m[:], jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m[:] - m_new)
        p = jnp.exp(s - m_new)
        l[:] = l[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc[:] = acc[:] * alpha + _dot_f32(p.astype(v_ref.dtype), v_ref[0])
        m[:] = m_new

    @pl.when(j == nk - 1)
    def _():
        lsafe = jnp.where(l[:] == 0.0, 1.0, l[:])
        o_ref[0] = (acc[:] / lsafe).astype(o_ref.dtype)
        # lse rides as (BH, 1, Sq): a (1, 1, bq) block satisfies Mosaic's
        # second-minor-divisible-by-8-or-full rule, which a (1, bq) block of
        # a (BH, Sq) array does not.
        lse_ref[0, 0] = (m[:] + jnp.log(lsafe))[:, 0]


def _dkdv_kernel(*refs, scale, causal, bq, bk, nq, has_bias, off):
    if has_bias:
        (q_ref, k_ref, v_ref, b_ref, do_ref, lse_ref, dl_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
        b_ref = None
    j, i = pl.program_id(1), pl.program_id(2)   # grid: (bh, kv, q)

    @pl.when(i == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @_when_live(i, j, causal=causal, bq=bq, bk=bk, off=off)
    def _():
        s = _scores(q_ref[0], k_ref[0], b_ref, i, j,
                    scale=scale, causal=causal, bq=bq, bk=bk,
                    off=off)
        p = jnp.exp(s - lse_ref[0, 0][:, None])               # (bq, bk) fp32
        dof = do_ref[0]
        dv_acc[:] += _dot_f32(p.astype(dof.dtype), dof, trans_a=True)
        dp = _dot_f32(dof, v_ref[0], trans_b=True)            # (bq, bk)
        ds = p * (dp - dl_ref[0, 0][:, None]) * scale
        dk_acc[:] += _dot_f32(ds.astype(q_ref.dtype), q_ref[0], trans_a=True)

    @pl.when(i == nq - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _dq_kernel(*refs, scale, causal, bq, bk, nk, has_bias, off):
    if has_bias:
        (q_ref, k_ref, v_ref, b_ref, do_ref, lse_ref, dl_ref,
         dq_ref, dq_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
         dq_ref, dq_acc) = refs
        b_ref = None
    i, j = pl.program_id(1), pl.program_id(2)   # grid: (bh, q, kv)

    @pl.when(j == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @_when_live(i, j, causal=causal, bq=bq, bk=bk, off=off)
    def _():
        s = _scores(q_ref[0], k_ref[0], b_ref, i, j,
                    scale=scale, causal=causal, bq=bq, bk=bk,
                    off=off)
        p = jnp.exp(s - lse_ref[0, 0][:, None])
        dp = _dot_f32(do_ref[0], v_ref[0], trans_b=True)
        ds = p * (dp - dl_ref[0, 0][:, None]) * scale
        dq_acc[:] += _dot_f32(ds.astype(k_ref.dtype), k_ref[0])

    @pl.when(j == nk - 1)
    def _():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


# Deferred pallas import (the module must import on hosts without pallas
# deps); bound at first kernel use, mirroring layer_norm.py's local imports.
pl = None
pltpu = None


def _bind_pallas():
    global pl, pltpu
    if pl is None:
        from jax.experimental import pallas as _pl
        from jax.experimental.pallas import tpu as _pltpu
        pl, pltpu = _pl, _pltpu


def _pick_blocks(sq: int, sk: int):
    bq = 256 if sq % 256 == 0 else 128
    bk = 256 if sk % 256 == 0 else 128
    return bq, bk


def _kernel_ok(q, k, *more) -> bool:
    sq, sk, d = q.shape[1], k.shape[1], q.shape[-1]
    if sq % 128 or sk % 128 or d % 8:
        return False
    if not _cfg.use_pallas_for(q, k, *more):
        return False
    return True


def _pad_head(x):
    """Pad the head dim up to a lane multiple when it isn't one.

    Kernel blocks always span the full head dim, and Mosaic accepts a last
    block dim equal to the overall array dim — so half-lane multiples
    (64, 128, 192, ...) run unpadded; ragged head dims (80, 96, ...) pay a
    pad to the next lane multiple.  Zeros change neither QK^T nor the value
    columns sliced back off."""
    d = x.shape[-1]
    if d % 64:
        pad = (-d) % 128
        x = jnp.pad(x, ((0, 0),) * (x.ndim - 1) + ((0, pad),))
    return x


def _fold(x):
    """(B, S, H, D) -> (B*H, S, D)."""
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _unfold(x, b, h):
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _bias_spec(bk, h, kv_axis=2):
    # bias rides as (B, 1, Sk) (same Mosaic tiling rule as lse); grid dim 0
    # runs over B*H, so the index map folds the head back out with a static
    # integer division.  ``kv_axis`` names which grid position (1 or 2)
    # walks kv blocks — it differs per kernel.
    return pl.BlockSpec(
        (1, 1, bk), lambda *g, h=h, a=kv_axis: (g[0] // h, 0, g[a]))


def _attn_fwd_pallas(q, k, v, bias, causal, scale, h):
    _bind_pallas()
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq, bk = _pick_blocks(sq, sk)
    nq, nk = sq // bq, sk // bk

    mat = lambda bs, im: pl.BlockSpec((1, bs, d), im)
    in_specs = [mat(bq, lambda b, i, j: (b, i, 0)),
                mat(bk, lambda b, i, j: (b, j, 0)),
                mat(bk, lambda b, i, j: (b, j, 0))]
    operands = [q, k, v]
    if bias is not None:
        in_specs.append(_bias_spec(bk, h))
        operands.append(bias[:, None, :])

    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nk=nk, has_bias=bias is not None,
                          off=sk - sq),
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=[mat(bq, lambda b, i, j: (b, i, 0)),
                   pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i))],
        out_shape=[sds((bh, sq, d), q.dtype, q, k, v),
                   sds((bh, 1, sq), jnp.float32, q, k, v)],
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32),
                        pltpu.VMEM((bq, 1), jnp.float32),
                        pltpu.VMEM((bq, 1), jnp.float32)],
        interpret=_cfg.INTERPRET,
    )(*operands)
    return o, lse


def _attn_bwd_pallas(q, k, v, bias, causal, scale, h, o, lse, do,
                     dlse=None):
    _bind_pallas()
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq, bk = _pick_blocks(sq, sk)
    nq, nk = sq // bq, sk // bk

    # delta_i = sum_d dO_i O_i — the d(logsumexp) correction; a cheap fused
    # elementwise+reduce, left to XLA rather than a third kernel.  Carried
    # (BH, 1, Sq) like lse (see the fwd kernel's tiling note).  When the lse
    # output itself carries a cotangent (flash_attention_with_lse — the ring
    # combine differentiates through it), it folds in here: dS = P∘(dP − Δ)
    # gains the term dlse_i·P_ij because ∂lse_i/∂S_ij = P_ij, i.e.
    # Δ_i := Δ_i − dlse_i.
    dl = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                 axis=-1)[:, None, :]
    if dlse is not None:
        dl = dl - dlse

    mat = lambda bs, im: pl.BlockSpec((1, bs, d), im)
    row = lambda bs, im: pl.BlockSpec((1, 1, bs), im)

    common = dict(scale=scale, causal=causal, bq=bq, bk=bk,
                  has_bias=bias is not None, off=sk - sq)
    qkv_specs = lambda qi, ki, kva: (
        [mat(bq, qi), mat(bk, ki), mat(bk, ki)]
        + ([_bias_spec(bk, h, kv_axis=kva)] if bias is not None else []))
    operands = [q, k, v] + ([bias[:, None, :]] if bias is not None else [])

    dk, dv = pl.pallas_call(
        functools.partial(_dkdv_kernel, nq=nq, **common),
        grid=(bh, nk, nq),   # kv outer, q inner (accumulate over q)
        in_specs=qkv_specs(lambda b, j, i: (b, i, 0),
                           lambda b, j, i: (b, j, 0), 1)
        + [mat(bq, lambda b, j, i: (b, i, 0)),     # do
           row(bq, lambda b, j, i: (b, 0, i)),     # lse
           row(bq, lambda b, j, i: (b, 0, i))],    # delta
        out_specs=[mat(bk, lambda b, j, i: (b, j, 0)),
                   mat(bk, lambda b, j, i: (b, j, 0))],
        out_shape=[sds((bh, sk, d), k.dtype, q, k, v, do),
                   sds((bh, sk, d), v.dtype, q, k, v, do)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=_cfg.INTERPRET,
    )(*operands, do, lse, dl)

    (dq,) = pl.pallas_call(
        functools.partial(_dq_kernel, nk=nk, **common),
        grid=(bh, nq, nk),   # q outer, kv inner (accumulate over kv)
        in_specs=qkv_specs(lambda b, i, j: (b, i, 0),
                           lambda b, i, j: (b, j, 0), 2)
        + [mat(bq, lambda b, i, j: (b, i, 0)),
           row(bq, lambda b, i, j: (b, 0, i)),
           row(bq, lambda b, i, j: (b, 0, i))],
        out_specs=[mat(bq, lambda b, i, j: (b, i, 0))],
        out_shape=[sds((bh, sq, d), q.dtype, q, k, v, do)],
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=_cfg.INTERPRET,
    )(*operands, do, lse, dl)
    return dq, dk, dv


# --------------------------------------------------------------------------
# Public ops with custom VJP.  flash_attention and flash_attention_with_lse
# share one dispatch pipeline (_lse_fwd / _bwd_dispatch); the only
# difference is whether the row logsumexp is exposed to the caller (and may
# therefore carry a cotangent).
# --------------------------------------------------------------------------

def _lse_fwd(q, k, v, bias, causal, scale):
    """Shared forward: (o, lse_public (B,H,Sq), lse_folded (BH,1,Sq)|None).

    lse_folded is None exactly when the XLA reference path ran (the backward
    then differentiates the reference instead of running the kernels)."""
    if causal and q.shape[1] > k.shape[1]:
        # Bottom-right alignment would leave the first Sq-Sk query rows with
        # no visible keys at all — there is no meaningful gradient for such
        # rows (and the kernel's recomputed-softmax backward would disagree
        # with autodiff on them), so the configuration is rejected outright.
        raise ValueError(
            f"causal attention needs Sq <= Sk (bottom-right alignment), got "
            f"Sq={q.shape[1]} > Sk={k.shape[1]}")
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    args = (q, k, v) + (() if bias is None else (bias,))
    b, sq, h, d = q.shape
    if not _kernel_ok(*args):
        o, lse = _reference_pair(q, k, v, bias, causal, scale)
        return o, lse, None
    qf, kf, vf = (_pad_head(_fold(x)) for x in (q, k, v))
    o, lse = _attn_fwd_pallas(qf, kf, vf, bias, causal, scale, h)
    return (_unfold(o[..., :d], b, h), lse[:, 0, :].reshape(b, h, sq), lse)


def _bwd_dispatch(causal, scale, res, do, dlse):
    """Shared backward.  dlse is the lse cotangent (None for the plain op)."""
    q, k, v, bias, o, lse_folded = res
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if lse_folded is None:
        if dlse is None:
            f = lambda q, k, v: attention_reference(q, k, v, bias, causal,
                                                    scale)
            _, vjp = jax.vjp(f, q, k, v)
            dq, dk, dv = vjp(do)
        else:
            f = lambda q, k, v: _reference_pair(q, k, v, bias, causal, scale)
            _, vjp = jax.vjp(f, q, k, v)
            dq, dk, dv = vjp((do, dlse))
    else:
        b, sq, h, d = q.shape
        qf, kf, vf, of, dof = (_pad_head(_fold(x)) for x in (q, k, v, o, do))
        dlse_f = None if dlse is None else \
            dlse.astype(jnp.float32).reshape(b * h, 1, sq)
        dq, dk, dv = _attn_bwd_pallas(qf, kf, vf, bias, causal, scale, h,
                                      of, lse_folded, dof, dlse=dlse_f)
        dq, dk, dv = (_unfold(g[..., :d], b, h) for g in (dq, dk, dv))
    dbias = None if bias is None else jnp.zeros_like(bias)  # constant mask
    return dq, dk, dv, dbias


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash_attention_op(q, k, v, bias, causal, scale):
    o, _, _ = _lse_fwd(q, k, v, bias, causal, scale)
    return o


def _flash_fwd_vjp(q, k, v, bias, causal, scale):
    o, _, lse_folded = _lse_fwd(q, k, v, bias, causal, scale)
    return o, (q, k, v, bias, o, lse_folded)


def _flash_bwd_vjp(causal, scale, res, do):
    return _bwd_dispatch(causal, scale, res, do, None)


_flash_attention_op.defvjp(_flash_fwd_vjp, _flash_bwd_vjp)


def flash_attention(q, k, v, bias=None, causal: bool = False,
                    scale: Optional[float] = None):
    """Memory-efficient multi-head attention.

    q: (B, Sq, H, D); k, v: (B, Sk, H, D); bias: optional (B, Sk) additive
    key bias (finite values; use ~-1e9 for masked keys); returns
    (B, Sq, H, D) in q's dtype.  Softmax is fp32.  Falls back to the XLA
    reference off-TPU or when shapes don't tile (S % 128, tiny sequences).

    ``bias`` is treated as a constant MASK: ``lax.stop_gradient`` is
    applied to it at this boundary, so differentiating w.r.t. a bias input
    yields structurally zero gradients on every path (kernel and fallback
    alike).  Do not route a *learned* bias (ALiBi-style scores etc.)
    through it — the parameter would not train; use explicit scores for
    that.
    """
    if bias is not None:
        bias = lax.stop_gradient(bias)
    return _flash_attention_op(q, k, v, bias, causal, scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash_attention_with_lse_op(q, k, v, bias, causal, scale):
    o, lse, _ = _lse_fwd(q, k, v, bias, causal, scale)
    return o, lse


def _flash_lse_fwd_vjp(q, k, v, bias, causal, scale):
    o, lse_pub, lse_folded = _lse_fwd(q, k, v, bias, causal, scale)
    return (o, lse_pub), (q, k, v, bias, o, lse_folded)


def _flash_lse_bwd_vjp(causal, scale, res, cts):
    do, dlse = cts
    return _bwd_dispatch(causal, scale, res, do, dlse)


_flash_attention_with_lse_op.defvjp(_flash_lse_fwd_vjp, _flash_lse_bwd_vjp)


def flash_attention_with_lse(q, k, v, bias=None, causal: bool = False,
                             scale: Optional[float] = None):
    """:func:`flash_attention` that also returns the row logsumexp.

    Returns ``(out, lse)`` with ``out``: (B, Sq, H, D) in q's dtype and
    ``lse``: (B, H, Sq) fp32.  The composable form: ring/blockwise context
    parallelism (parallel/context_parallel.py) merges per-chunk results with
    the logsumexp-weighted combine.  Unlike the bias argument (constant
    mask, stop_gradient'ed at this boundary exactly like
    :func:`flash_attention`), ``lse`` is fully differentiable — the combine
    weights backpropagate through it (the kernel backward absorbs the
    cotangent into its Δ correction: ∂lse_i/∂S_ij = P_ij).
    """
    if bias is not None:
        bias = lax.stop_gradient(bias)
    return _flash_attention_with_lse_op(q, k, v, bias, causal, scale)
