"""Fused LayerNorm: Pallas TPU kernel with custom VJP + XLA reference.

Reference (csrc/layer_norm_cuda.cpp + layer_norm_cuda_kernel.cu, exposed as
apex.normalization.FusedLayerNorm; SURVEY.md §2.1): a CUDA kernel computes
Welford mean/var per row and normalizes in one pass; the backward kernel
produces dx and the dgamma/dbeta reductions.

TPU-native design: one Pallas kernel per pass, gridded over row blocks.  Rows
live in VMEM; mean/var are row reductions on the VPU; the affine transform is
fused into the same kernel (one HBM round-trip, which is the entire point —
LayerNorm is bandwidth-bound).  Stats are computed in fp32 regardless of the
input dtype (the reference's MixedFusedLayerNorm behavior: bf16 in/out, fp32
params and stats).  The backward recomputes x̂ from the saved fp32 (mean,
rstd) instead of saving it — rematerialization trades a cheap VPU op for HBM.

``layer_norm`` is the public entry: custom_vjp, Pallas on TPU, pure-XLA
elsewhere (tests compare both against torch.nn.LayerNorm goldens).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_example_tpu.ops._vma import align_param_grad, sds

from apex_example_tpu.ops import _config as _cfg


def _use_pallas(x, *more) -> bool:
    if _cfg.INTERPRET:
        return _cfg.use_pallas_for(x, *more)
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    # Lane-dim constraint: hidden must tile to 128 for a clean kernel.
    return x.shape[-1] % 128 == 0 and x.shape[-1] >= 128


# --------------------------------------------------------------------------
# XLA reference path (also the golden for kernel tests).
# --------------------------------------------------------------------------

def layer_norm_reference(x, gamma, beta, eps: float = 1e-5):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + eps)
    y = y * gamma.astype(jnp.float32) + beta.astype(jnp.float32)
    return y.astype(x.dtype)


def rms_norm_reference(x, gamma, eps: float = 1e-5):
    xf = x.astype(jnp.float32)
    rstd = lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * rstd * gamma.astype(jnp.float32)).astype(x.dtype)


# --------------------------------------------------------------------------
# Pallas kernels.
# --------------------------------------------------------------------------

def _fwd_kernel(x_ref, g_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps):
    xf = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = lax.rsqrt(var + eps)
    xhat = xc * rstd
    y = xhat * g_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    # Stats are (block, 1) 2-D: rank-1 outputs would pin the row block to
    # Mosaic's 1024-element 1-D tiling (hit on real TPU by hidden=768);
    # rank-2 blocks only need the usual (8, 128) tiling.
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _bwd_kernel(x_ref, g_ref, mean_ref, rstd_ref, dy_ref,
                dx_ref, dg_ref, db_ref):
    xf = x_ref[:].astype(jnp.float32)
    dyf = dy_ref[:].astype(jnp.float32)
    mean = mean_ref[:]          # (block, 1)
    rstd = rstd_ref[:]
    xhat = (xf - mean) * rstd
    gamma = g_ref[:].astype(jnp.float32)

    # dgamma/dbeta: partial sums per row-block, accumulated across the grid.
    dg_ref[:] += jnp.sum(dyf * xhat, axis=0)
    db_ref[:] += jnp.sum(dyf, axis=0)

    # dx = rstd * (dy*g - mean(dy*g) - xhat * mean(dy*g*xhat))
    wdy = dyf * gamma
    c1 = jnp.mean(wdy, axis=-1, keepdims=True)
    c2 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
    dx_ref[:] = (rstd * (wdy - c1 - xhat * c2)).astype(dx_ref.dtype)


def _pick_block_rows(n_rows: int, hidden: int, dtype,
                     budget: int = 1024 * 1024) -> int:
    # Row blocks are multiples of 128 (sublane-friendly, and the (block, 1)
    # stat outputs only face the standard 2-D tiling).  ``budget`` bounds the
    # x-block bytes; the kernel's fp32 temporaries multiply it ~4-6x on the
    # VMEM stack (Mosaic's 16 MiB limit — the backward kernel holds x, dy,
    # dx plus four fp32 intermediates, so it passes a halved budget).
    bytes_per = jnp.dtype(dtype).itemsize
    target = budget // max(1, hidden * bytes_per)
    block = max(128, (target // 128) * 128)
    return min(block, max(128, ((n_rows + 127) // 128) * 128))


def _specs(pl, pltpu, block, h):
    """(mat, vec, stat) BlockSpec constructors shared by fwd/bwd plumbing."""
    mat = lambda: pl.BlockSpec((block, h), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)
    vec = lambda: pl.BlockSpec((h,), lambda i: (0,),
                               memory_space=pltpu.VMEM)
    stat = lambda: pl.BlockSpec((block, 1), lambda i: (i, 0),
                                memory_space=pltpu.VMEM)
    return mat, vec, stat


def _norm_fwd_pallas(x2d, gamma, beta, eps):
    """Shared fwd plumbing for LayerNorm (beta given) and RMSNorm (beta
    None): block picking, row padding, specs, and the (block, 1) stat rule.

    Returns (y, mean|None, rstd)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    with_mean = beta is not None
    n, h = x2d.shape
    block = _pick_block_rows(n, h, x2d.dtype)
    pad = (-n) % block
    if pad:
        x2d = jnp.pad(x2d, ((0, pad), (0, 0)))
    np_ = x2d.shape[0]

    mat, vec, stat = _specs(pl, pltpu, block, h)
    n_stats = 2 if with_mean else 1
    outs = pl.pallas_call(
        functools.partial(_fwd_kernel if with_mean else _rms_fwd_kernel,
                          eps=eps),
        grid=(np_ // block,),
        in_specs=[mat()] + [vec()] * (2 if with_mean else 1),
        out_specs=[mat()] + [stat()] * n_stats,
        out_shape=([sds((np_, h), x2d.dtype, x2d)]
                   + [sds((np_, 1), jnp.float32, x2d)] * n_stats),
        interpret=_cfg.INTERPRET,
    )(*([x2d, gamma, beta] if with_mean else [x2d, gamma]))
    if with_mean:
        y, mean, rstd = outs
        return y[:n], mean[:n, 0], rstd[:n, 0]
    y, rstd = outs
    return y[:n], None, rstd[:n, 0]


def _norm_bwd_pallas(x2d, gamma, mean, rstd, dy2d):
    """Shared bwd plumbing: LayerNorm when ``mean`` is given (emits dx, dg,
    db), RMSNorm when ``mean`` is None (emits dx, dg)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    with_mean = mean is not None
    n, h = x2d.shape
    block = _pick_block_rows(n, h, x2d.dtype, budget=512 * 1024)
    pad = (-n) % block
    if pad:
        x2d = jnp.pad(x2d, ((0, pad), (0, 0)))
        dy2d = jnp.pad(dy2d, ((0, pad), (0, 0)))
        if with_mean:
            mean = jnp.pad(mean, (0, pad))
        rstd = jnp.pad(rstd, (0, pad))  # padded rows: rstd 0 => contribute 0
    stats2 = ([mean[:, None]] if with_mean else []) + [rstd[:, None]]
    np_ = x2d.shape[0]
    n_grads = 2 if with_mean else 1     # dg (+ db)

    def bwd_with_init(*refs):
        from jax.experimental import pallas as pl2

        @pl2.when(pl2.program_id(0) == 0)
        def _():
            # the trailing refs are the across-grid accumulators (dg [, db])
            for r in refs[-n_grads:]:
                r[:] = jnp.zeros_like(r)
        (_bwd_kernel if with_mean else _rms_bwd_kernel)(*refs)

    mat, vec, stat = _specs(pl, pltpu, block, h)
    outs = pl.pallas_call(
        bwd_with_init,
        grid=(np_ // block,),
        in_specs=([mat(), vec()] + [stat()] * len(stats2) + [mat()]),
        # dgamma/dbeta accumulate across sequential grid steps: every step
        # maps to the same block (TPU grids are sequential).
        out_specs=[mat()] + [vec()] * n_grads,
        out_shape=([sds((np_, h), x2d.dtype, x2d, dy2d)]
                   + [sds((h,), jnp.float32, x2d, dy2d, gamma)] * n_grads),
        interpret=_cfg.INTERPRET,
    )(x2d, gamma, *stats2, dy2d)
    dx = outs[0][:n] if pad else outs[0]
    return (dx, *outs[1:])


# --------------------------------------------------------------------------
# Public op with custom VJP.
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def layer_norm(x, gamma, beta, eps: float = 1e-5):
    """Fused LayerNorm over the last axis.  x: (..., H); gamma/beta: (H,)."""
    y, _, _ = _layer_norm_fwd(x, gamma, beta, eps)
    return y


def _layer_norm_fwd(x, gamma, beta, eps):
    shape = x.shape
    h = shape[-1]
    x2d = x.reshape(-1, h)
    if _use_pallas(x2d):
        y, mean, rstd = _norm_fwd_pallas(x2d, gamma, beta, eps)
    else:
        xf = x2d.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1)
        var = jnp.mean(jnp.square(xf - mean[:, None]), axis=-1)
        rstd = lax.rsqrt(var + eps)
        y = ((xf - mean[:, None]) * rstd[:, None]
             * gamma.astype(jnp.float32) + beta.astype(jnp.float32)
             ).astype(x.dtype)
    return y.reshape(shape), mean, rstd


def _layer_norm_fwd_vjp(x, gamma, beta, eps):
    y, mean, rstd = _layer_norm_fwd(x, gamma, beta, eps)
    return y, (x, gamma, mean, rstd)


def _layer_norm_bwd_vjp(eps, res, dy):
    del eps
    x, gamma, mean, rstd = res
    shape = x.shape
    h = shape[-1]
    x2d = x.reshape(-1, h)
    dy2d = dy.reshape(-1, h)
    if _use_pallas(x2d, dy2d):
        dx, dg, db = _norm_bwd_pallas(x2d, gamma, mean, rstd, dy2d)
    else:
        xf = x2d.astype(jnp.float32)
        dyf = dy2d.astype(jnp.float32)
        xhat = (xf - mean[:, None]) * rstd[:, None]
        gf = gamma.astype(jnp.float32)
        dg = jnp.sum(dyf * xhat, axis=0)
        db = jnp.sum(dyf, axis=0)
        wdy = dyf * gf
        c1 = jnp.mean(wdy, axis=-1, keepdims=True)
        c2 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
        dx = (rstd[:, None] * (wdy - c1 - xhat * c2)).astype(x.dtype)
    # Mesh-invariant gamma/beta get mesh-invariant (psum-ed) grads — the
    # reduction regular primitives receive from vma-aware AD (see
    # _vma.align_param_grad).
    dg = align_param_grad(dg, gamma)
    db = align_param_grad(db, gamma)
    return (dx.reshape(shape), dg.astype(gamma.dtype), db.astype(gamma.dtype))


layer_norm.defvjp(_layer_norm_fwd_vjp, _layer_norm_bwd_vjp)


# --------------------------------------------------------------------------
# FusedRMSNorm (reference: the later apex FusedRMSNorm in
# apex/normalization/fused_layer_norm.py, SURVEY.md §3.4): LayerNorm minus
# the mean subtraction — rstd over E[x²], no beta.  Same blocking and the
# same rank-2 (rows, 1) stat-output rule as layer_norm above.
# --------------------------------------------------------------------------

def _rms_fwd_kernel(x_ref, g_ref, y_ref, rstd_ref, *, eps):
    xf = x_ref[:].astype(jnp.float32)
    rstd = lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    y_ref[:] = (xf * rstd * g_ref[:].astype(jnp.float32)).astype(y_ref.dtype)
    rstd_ref[:] = rstd


def _rms_bwd_kernel(x_ref, g_ref, rstd_ref, dy_ref, dx_ref, dg_ref):
    xf = x_ref[:].astype(jnp.float32)
    dyf = dy_ref[:].astype(jnp.float32)
    rstd = rstd_ref[:]                   # (block, 1)
    xhat = xf * rstd
    wdy = dyf * g_ref[:].astype(jnp.float32)

    dg_ref[:] += jnp.sum(dyf * xhat, axis=0)
    c2 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
    dx_ref[:] = (rstd * (wdy - xhat * c2)).astype(dx_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm(x, gamma, eps: float = 1e-5):
    """Fused RMSNorm over the last axis.  x: (..., H); gamma: (H,)."""
    y, _ = _rms_norm_fwd(x, gamma, eps)
    return y


def _rms_norm_fwd(x, gamma, eps):
    shape = x.shape
    h = shape[-1]
    x2d = x.reshape(-1, h)
    if _use_pallas(x2d):
        y, _, rstd = _norm_fwd_pallas(x2d, gamma, None, eps)
    else:
        xf = x2d.astype(jnp.float32)
        rstd = lax.rsqrt(jnp.mean(xf * xf, axis=-1) + eps)
        y = (xf * rstd[:, None] * gamma.astype(jnp.float32)).astype(x.dtype)
    return y.reshape(shape), rstd


def _rms_norm_fwd_vjp(x, gamma, eps):
    y, rstd = _rms_norm_fwd(x, gamma, eps)
    return y, (x, gamma, rstd)


def _rms_norm_bwd_vjp(eps, res, dy):
    del eps
    x, gamma, rstd = res
    shape = x.shape
    h = shape[-1]
    x2d = x.reshape(-1, h)
    dy2d = dy.reshape(-1, h)
    if _use_pallas(x2d, dy2d):
        dx, dg = _norm_bwd_pallas(x2d, gamma, None, rstd, dy2d)
    else:
        xf = x2d.astype(jnp.float32)
        dyf = dy2d.astype(jnp.float32)
        xhat = xf * rstd[:, None]
        wdy = dyf * gamma.astype(jnp.float32)
        dg = jnp.sum(dyf * xhat, axis=0)
        c2 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
        dx = (rstd[:, None] * (wdy - xhat * c2)).astype(x.dtype)
    dg = align_param_grad(dg, gamma)
    return dx.reshape(shape), dg.astype(gamma.dtype)


rms_norm.defvjp(_rms_norm_fwd_vjp, _rms_norm_bwd_vjp)
