"""Disaggregated prefill/decode serving: roles + KV-handoff transport.

The interleaved engine (serve/engine.py, role "both") runs prefill
chunks and decode steps through ONE [SLOTS, block_size]-wide compiled
program: a tick that advances three long prefills and one decoding slot
charges the decoding slot the full chunked-prefill geometry.  The
production-TPU serving shape (the Gemma serving paper, PAPERS.md)
splits the two phases onto separate worker pools instead:

- a **prefill worker** (role "prefill") admits fresh requests, chunk-
  prefills each prompt into its local paged arena, samples the FIRST
  token, then terminates the request locally with status "handoff",
  shipping its KV blocks through a transport;
- a **decode worker** (role "decode") scatters each payload into its
  own arena (``BlockPool.admit_prefilled``) and decodes from there —
  its compiled step is [SLOTS, 1]-wide, so decode ticks stop paying
  for prefill lanes entirely.  TPOT on the decode role beats the
  interleaved baseline because every one of its ticks is the cheap
  program.

The handoff payload (:class:`KvHandoff`) is storage-dtype-exact: int8
arenas ship int8 rows plus their bf16 per-token block scales
(quant/kv.py), full-precision arenas ship full-precision rows.  The
copy is deep by construction — a COW-shared prefix block's bytes are
gathered out of the arena, so refcounts on the prefill side stay
consistent (the shared block parks in the reusable cache at eviction)
and the decode side can never alias it.

Transports:

- :class:`QueueTransport` — in-process deque, what the tier-1
  comparison test and :func:`run_disagg` drive;
- :class:`FileTransport` — a spool directory of ``handoff-*.npz``
  files written atomically (tmp + rename) plus a ``close.json``
  sentinel, connecting a ``serve.py --role prefill`` process to one or
  more ``--role decode`` processes with no shared memory.

The file spool speaks a LEASED, crash-safe protocol (ISSUE 15):

- **claim by atomic rename** — a consumer takes a spool file by
  renaming ``handoff-*.npz`` to ``*.npz.claim-<worker>-<deadline>``;
  the loser of a rename race simply moves on, so N decode workers can
  share one spool without coordination;
- **wall-clock lease** — the claim name carries an epoch deadline.  An
  EXPIRED claim is reclaimed by renaming it back to the spool name, so
  ANY worker can redeliver a dead peer's claimed-but-unacked handoffs;
  a worker that comes back under its own id adopts its pre-crash
  claims immediately (no lease wait) — both paths mark the next
  delivery ``redelivered``;
- **ack-by-delete at admission** — the consumer deletes the claim file
  once ``admit_handoff`` consumed the payload.  A worker that dies
  between admit and ack leaves the claim on disk; the redelivery is
  detected against the decode engine's seen-set (idempotent admission
  on handoff uid) and acked without a second scatter;
- **quarantine, never crash** — a corrupt/truncated payload renames to
  ``*.bad`` and surfaces through ``on_quarantine`` (serve.py writes a
  ``kv_handoff`` direction "quarantine" record) while the worker keeps
  ticking.

Determinism: handoffs are sequence-numbered at send time and admitted
in that order; a payload that exceeds the decode worker's free blocks
is REQUEUED at the head (``admit_handoff`` returns False leaving no
state behind) and retried after evictions free capacity — never
dropped, never a crash.

Both sides emit schema-v13 ``kv_handoff`` records (direction
out/in/quarantine, with ``redelivered``/``duplicate`` provenance);
``tools/ci_gate.py --disagg-stream`` checks a deployment's recorded
role streams for conservation — redelivery episodes tolerated, but
exactly one EFFECTIVE admission and exactly one terminal status per
handoff uid — and ``tools/serve_report.py`` renders the HANDOFF and
REDELIVERY lines.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from apex_example_tpu.resilience.faults import FaultInjected
from apex_example_tpu.serve.queue import Completion, Request


@dataclass
class KvHandoff:
    """One request's prefilled KV state in flight between roles.

    ``tokens`` is the full token list so far — the prompt plus the
    prefill worker's first sampled token; ``fill`` counts tokens whose
    KV the payload covers (== prompt length); ``payload`` maps each
    arena leaf's path string to a ``[n_blocks, block_size, ...]`` host
    array in the leaf's storage dtype."""

    uid: str
    request: Request
    tokens: List[int]
    fill: int
    block_size: int
    kv_dtype: str
    payload: Dict[str, np.ndarray]
    payload_bytes: int
    t_out_wall: float
    src: str = ""
    # Payload kind (ISSUE 20): "handoff" is the one-shot prefill ->
    # decode transfer (fill == prompt length, first token sampled);
    # "migration" is a LIVE mid-flight snapshot (fill == cursor, any
    # number of generated tokens, possibly still mid-prefill) shipped
    # by ServeEngine.extract_live / drain(migrate=...).  Same wire
    # format, same lease/ack/redelivery protocol; the destination
    # engine keys its record type and counters on it.
    kind: str = "handoff"
    requeued: int = 0       # deferred-admission episodes, decode side
    # Delivery provenance (ISSUE 15): nonzero when this delivery came
    # from a reclaimed/adopted lease rather than a fresh spool file —
    # the decode side's kv_handoff record and the fleet scenario
    # checks key on it.
    redelivered: int = 0
    # prefill-side latency trail (wall-independent, for the kv_handoff
    # record): the request's measured TTFT/queue wait up to handoff.
    ttft_ms: Optional[float] = None
    queue_wait_ms: Optional[float] = None
    spool_file: Optional[str] = None   # FileTransport bookkeeping


class QueueTransport:
    """In-process handoff channel: FIFO, closed explicitly by the
    prefill side once its workload is drained."""

    def __init__(self):
        self._q: deque = deque()
        self._closed = False
        self.sent = 0

    def send(self, handoff: KvHandoff) -> None:
        if self._closed:
            raise RuntimeError("transport is closed")
        self.sent += 1
        self._q.append(handoff)

    def poll(self) -> List[KvHandoff]:
        out = list(self._q)
        self._q.clear()
        return out

    def ack(self, handoff: KvHandoff) -> None:
        """Admission consumed the handoff (no-op in process: nothing
        outlives the deque)."""

    def renew(self, handoffs) -> None:
        """Lease renewal (no-op in process: no leases)."""

    def close(self) -> None:
        self._closed = True

    def finished(self) -> bool:
        """No more handoffs will ever arrive (closed and drained)."""
        return self._closed and not self._q


class FileTransport:
    """Leased file-spool handoff channel between role processes.

    The prefill side writes ``handoff-<seq>-<uid>.npz`` (payload arrays
    plus a JSON meta member) via tmp-file + atomic rename, then a
    ``close.json`` sentinel carrying the total count.  A decode side
    CLAIMS files by atomic rename (``*.npz`` ->
    ``*.npz.claim-<worker>-<deadline>``), loads them in sequence order
    and acks-by-delete at admission; expired claims rename back to the
    spool name so any peer redelivers them, and a worker returning
    under the same ``worker`` id adopts its own pre-crash claims
    without waiting out the lease.  Single producer, ANY number of
    consumers (one live instance per ``worker`` id)."""

    SENTINEL = "close.json"

    def __init__(self, path: str, worker: Optional[str] = None,
                 lease_s: float = 30.0, fault=None, on_quarantine=None):
        self.path = path
        os.makedirs(path, exist_ok=True)
        # Restart-safe sequence numbers: a producer that comes back
        # mid-stream must not clobber (or re-order under) the files its
        # predecessor already spooled.
        self._seq = 1 + max(
            (self._seq_of(n) for n in os.listdir(path)), default=-1)
        self.worker = worker or f"w{os.getpid()}"
        if "/" in self.worker or ".claim-" in self.worker:
            raise ValueError(f"bad worker id {self.worker!r}")
        self.lease_s = float(lease_s)
        # A handoff-kind resilience FaultPlan (handoff_torn /
        # sentinel_lost fire here on the producer side; the decode-side
        # kinds live in run_decode_role).
        self.fault = fault
        # on_quarantine(uid, spool_name, error, nbytes): called once per
        # corrupt payload parked at *.bad (serve.py writes the warn
        # record); quarantine never raises out of poll().
        self.on_quarantine = on_quarantine
        self.sent = 0
        self.quarantined = 0
        self.reclaimed = 0              # expired claims we renamed back
        self._expected: Optional[int] = None
        self._consumed = 0
        self._mine: set = set()         # claim names THIS instance holds
        self._redelivered: set = set()  # spool names whose next delivery
        #                                 is a redelivery (reclaim/adopt)

    @staticmethod
    def _seq_of(name: str) -> int:
        if name.startswith("handoff-"):
            try:
                return int(name.split("-", 2)[1])
            except (IndexError, ValueError):
                return -1
        return -1

    @staticmethod
    def _uid_of(spool_name: str) -> str:
        """The request uid embedded in ``handoff-<seq>-<uid>.npz``."""
        stem = spool_name[:-len(".npz")] if spool_name.endswith(".npz") \
            else spool_name
        return stem.split("-", 2)[-1]

    def pending_on_disk(self) -> int:
        """Spool files not yet acked — unclaimed files plus live claims
        (quarantined ``*.bad`` files are a disposition, not a
        backlog).  What a stopped decode worker leaves behind for the
        next one; serve.py counts these as stranded at a --steps cap."""
        n = 0
        for name in os.listdir(self.path):
            if name.startswith(".tmp-") or name.endswith(".bad"):
                continue
            if (name.startswith("handoff-") and name.endswith(".npz")) \
                    or ".claim-" in name:
                n += 1
        return n

    # ------------------------------------------------------ prefill side

    def send(self, handoff: KvHandoff) -> None:
        name = f"handoff-{self._seq:06d}-{handoff.uid}.npz"
        self._seq += 1
        req = handoff.request
        meta = {
            "uid": handoff.uid,
            "tokens": [int(t) for t in handoff.tokens],
            "fill": handoff.fill,
            "block_size": handoff.block_size,
            "kv_dtype": handoff.kv_dtype,
            "payload_bytes": handoff.payload_bytes,
            "t_out_wall": handoff.t_out_wall,
            "src": handoff.src,
            "kind": handoff.kind,
            "keys": list(handoff.payload.keys()),
            "request": {
                "prompt": [int(t) for t in req.prompt],
                "max_new_tokens": req.max_new_tokens,
                "temperature": req.temperature,
                "top_k": req.top_k,
                "eos_id": req.eos_id,
                # Migration round-trip (ISSUE 20): a live request's
                # scheduling identity must survive the wire — the
                # destination keeps honoring the tenant lane and both
                # deadline domains.
                "tenant": getattr(req, "tenant", "default"),
                "priority": getattr(req, "priority", 0),
                "deadline_s": req.deadline_s,
                "deadline_step": req.deadline_step,
            },
        }
        arrays = {f"a{i}": handoff.payload[k].view(np.uint8)
                  if handoff.payload[k].dtype.kind == "V"
                  else handoff.payload[k]
                  for i, k in enumerate(meta["keys"])}
        # bfloat16 has no portable npz spelling; ship raw bytes plus
        # the dtype names needed to reinterpret on the other side.
        meta["dtypes"] = [str(handoff.payload[k].dtype)
                          for k in meta["keys"]]
        meta["shapes"] = [list(handoff.payload[k].shape)
                          for k in meta["keys"]]
        tmp = os.path.join(self.path, f".tmp-{name}")
        with open(tmp, "wb") as fh:
            np.savez(fh, meta=np.frombuffer(
                json.dumps(meta).encode(), np.uint8), **arrays)
        if self.fault is not None and self.fault.kind == "handoff_torn" \
                and self.fault.due(self.sent + 1):
            # The torn-payload drill: ship only the first half of the
            # bytes.  The rename below is still atomic — this is a
            # CORRUPT payload (a producer died mid-serialize to a
            # non-atomic medium, bit rot in transit), not a torn
            # rename; the consumer must quarantine it, not crash.
            self.fault.take()
            size = os.path.getsize(tmp)
            with open(tmp, "r+b") as fh:
                fh.truncate(max(size // 2, 1))
        os.replace(tmp, os.path.join(self.path, name))
        self.sent += 1

    def close(self) -> None:
        if self.fault is not None and self.fault.kind == "sentinel_lost" \
                and self.fault.due(1):
            # The producer-died drill: the stream's end never announces
            # itself.  A decode worker sized with --handoff-idle-timeout
            # finishes what is spooled and exits instead of spinning.
            self.fault.take()
            return
        tmp = os.path.join(self.path, ".tmp-" + self.SENTINEL)
        with open(tmp, "w") as fh:
            json.dump({"handoffs": self.sent, "worker": self.worker,
                       "time": time.time()}, fh)
        os.replace(tmp, os.path.join(self.path, self.SENTINEL))

    # ------------------------------------------------------- decode side

    def poll(self) -> List[KvHandoff]:
        """Claim and load every claimable spool file, in sequence
        order.  Three passes over one directory listing:

        1. **reclaim/adopt** — a claim whose lease deadline passed (its
           holder is presumed dead), or ANY claim carrying our own
           ``worker`` id that this instance did not create (our
           predecessor's, pre-crash), renames back to the spool name;
           its next delivery is marked ``redelivered``.
        2. **claim** — every unclaimed spool file renames to
           ``*.claim-<worker>-<deadline>``; losing the rename race to a
           peer just skips the file.
        3. **load** — claimed files parse into :class:`KvHandoff`; a
           corrupt/truncated payload renames to ``*.bad`` and surfaces
           through ``on_quarantine`` instead of raising.

        Claimed files stay ON DISK until :meth:`ack` (admission
        consumed the handoff) — a worker that dies between poll and
        ack, or between admit and ack, strands nothing: the lease
        expires and a peer (or its own restart) redelivers."""
        now = time.time()
        out: List[KvHandoff] = []
        try:
            names = os.listdir(self.path)
        except OSError:  # pragma: no cover
            return out
        claimable = [n for n in names
                     if n.startswith("handoff-") and n.endswith(".npz")]
        for name in names:
            if ".claim-" not in name or name.endswith(".bad") \
                    or name in self._mine:
                continue
            base, _, rest = name.partition(".claim-")
            holder, _, deadline_s = rest.rpartition("-")
            try:
                expired = float(deadline_s) <= now
            except ValueError:
                expired = True          # malformed deadline: treat dead
            if holder != self.worker and not expired:
                continue                # a live peer's lease
            try:
                os.rename(os.path.join(self.path, name),
                          os.path.join(self.path, base))
            except OSError:
                continue                # raced another reclaimer
            self._redelivered.add(base)
            self.reclaimed += 1
            claimable.append(base)
        for base in sorted(set(claimable), key=self._seq_of):
            claim = f"{base}.claim-{self.worker}-{now + self.lease_s:.3f}"
            src = os.path.join(self.path, base)
            dst = os.path.join(self.path, claim)
            try:
                os.rename(src, dst)
            except OSError:
                continue                # a peer won the claim race
            self._mine.add(claim)
            try:
                handoff = self._load(dst)
            except Exception as e:  # noqa: BLE001 — quarantine, never crash
                self._quarantine(base, claim, e)
                continue
            handoff.spool_file = claim
            handoff.redelivered = 1 if base in self._redelivered else 0
            out.append(handoff)
        return out

    def _quarantine(self, base: str, claim: str, error: Exception) -> None:
        """Park a corrupt payload at ``<spool-name>.bad`` (a recorded
        disposition, outside every future claim scan) and tell the
        caller — the worker stays alive."""
        bad = os.path.join(self.path, base + ".bad")
        nbytes = 0
        try:
            nbytes = os.path.getsize(os.path.join(self.path, claim))
            os.replace(os.path.join(self.path, claim), bad)
        except OSError:  # pragma: no cover — raced a reclaim
            pass
        self._mine.discard(claim)
        self.quarantined += 1
        self._consumed += 1
        if self.on_quarantine is not None:
            self.on_quarantine(self._uid_of(base), base, error, nbytes)

    def renew(self, handoffs) -> None:
        """Extend the lease on claims THIS worker still holds (polled
        but not yet admitted — the deterministic-requeue wait when the
        pool is full).  Call once per drive-loop tick: without renewal
        a deferred admission outliving the lease would be reclaimed by
        a live peer and double-served.  Renewal is the same atomic
        rename as a claim; losing the race (a peer already reclaimed
        after a REAL expiry) is tolerated — the redelivery lands on
        whichever engine's seen-set wins."""
        now = time.time()
        for handoff in handoffs:
            name = getattr(handoff, "spool_file", None)
            if not name or name not in self._mine:
                continue
            base, _, rest = name.partition(".claim-")
            deadline_s = rest.rpartition("-")[2]
            try:
                deadline = float(deadline_s)
            except ValueError:
                deadline = now
            if deadline - now > self.lease_s / 2:
                continue                # plenty of lease left
            fresh = f"{base}.claim-{self.worker}-{now + self.lease_s:.3f}"
            try:
                os.rename(os.path.join(self.path, name),
                          os.path.join(self.path, fresh))
            except OSError:
                continue                # lost the lease for real
            self._mine.discard(name)
            self._mine.add(fresh)
            handoff.spool_file = fresh

    def ack(self, handoff: KvHandoff) -> None:
        """The consumer owns the handoff now (admitted, duplicate-
        detected, or terminally rejected): delete its claim file.  A
        FileNotFoundError means our lease expired and a peer reclaimed
        the file mid-decode — tolerated (the seen-set on whichever
        engine admits the redelivery keeps admission idempotent)."""
        name = handoff.spool_file
        if name:
            try:
                os.remove(os.path.join(self.path, name))
            except FileNotFoundError:
                pass
            self._mine.discard(name)
            handoff.spool_file = None
        self._consumed += 1

    def _load(self, full: str) -> KvHandoff:
        import ml_dtypes  # noqa: F401  (registers bfloat16 with numpy)
        with np.load(full) as z:
            meta = json.loads(bytes(z["meta"]).decode())
            payload = {}
            for i, key in enumerate(meta["keys"]):
                arr = z[f"a{i}"]
                want = np.dtype(meta["dtypes"][i])
                if arr.dtype != want:
                    arr = arr.view(want)
                payload[key] = arr.reshape(meta["shapes"][i])
        spec = meta["request"]
        req = Request(prompt=spec["prompt"],
                      max_new_tokens=int(spec["max_new_tokens"]),
                      temperature=float(spec.get("temperature", 0.0)),
                      top_k=int(spec.get("top_k", 0)),
                      eos_id=spec.get("eos_id"),
                      tenant=spec.get("tenant", "default"),
                      priority=int(spec.get("priority", 0)),
                      deadline_s=spec.get("deadline_s"),
                      deadline_step=spec.get("deadline_step"),
                      uid=meta["uid"])
        return KvHandoff(
            uid=meta["uid"], request=req, tokens=meta["tokens"],
            fill=int(meta["fill"]), block_size=int(meta["block_size"]),
            kv_dtype=meta["kv_dtype"],
            payload=payload,
            payload_bytes=int(meta["payload_bytes"]),
            t_out_wall=float(meta["t_out_wall"]),
            src=meta.get("src", ""),
            kind=meta.get("kind", "handoff"))

    def finished(self) -> bool:
        """No more handoffs will ever arrive for ANY worker: the
        producer closed the stream (sentinel on disk) and the spool is
        empty — no unclaimed files, no live claims.  Defined on the
        DIRECTORY rather than this instance's consumed count so N
        workers sharing one spool each exit exactly when the last
        file is acked, wherever it was acked."""
        sentinel = os.path.join(self.path, self.SENTINEL)
        if self._expected is None and os.path.exists(sentinel):
            try:
                with open(sentinel) as fh:
                    self._expected = int(json.load(fh)["handoffs"])
            except (OSError, ValueError, KeyError):
                self._expected = -1     # unreadable sentinel still closes
        return self._expected is not None and self.pending_on_disk() == 0


# ------------------------------------------------------------ drive loops


def run_prefill_role(engine, transport, max_steps: Optional[int] = None,
                     idle_wait_s: float = 0.0, stop=None,
                     on_tick=None) -> List[Completion]:
    """Drive a prefill-role engine over its (already submitted) queue,
    then close the transport — the decode side's end-of-stream signal.
    The engine itself ships each handoff at first-token time
    (``handoff_sink`` is the transport's ``send``)."""
    comps = engine.run(max_steps=max_steps, idle_wait_s=idle_wait_s,
                       stop=stop, on_tick=on_tick)
    transport.close()
    return comps


def run_decode_role(engine, transport, max_steps: Optional[int] = None,
                    idle_wait_s: float = 0.0, stop=None,
                    on_tick=None, fault=None,
                    idle_timeout_s: Optional[float] = None
                    ) -> List[Completion]:
    """Drive a decode-role engine off a transport: poll for handoffs,
    admit them IN ORDER (a handoff the pool cannot fit yet stays at the
    head and is retried next tick — deterministic requeue, never a
    drop), tick while there is work, exit once the transport is
    finished and every admitted request terminated.

    ``fault`` takes the decode-side handoff drills (ISSUE 15):
    ``handoff_crash_preack`` raises between the Nth successful admit
    and its ack — the claim survives on disk for redelivery — and
    ``handoff_dup`` redelivers the Nth admitted handoff once more (the
    engine's seen-set detects it and it is acked without a second
    scatter).  ``idle_timeout_s`` bounds how long an idle worker waits
    for an unfinished transport — the sentinel_lost shape: when the
    producer died without closing the stream, finish what is spooled
    and exit instead of spinning forever."""
    engine.queue.close()               # decode-role intake is the transport
    pending: deque = deque()
    admits = 0
    last_progress = time.time()
    while max_steps is None or engine.step_count < max_steps:
        if stop is not None and stop():
            break
        polled = transport.poll()
        if polled:
            pending.extend(polled)
            last_progress = time.time()
        if pending:
            # Keep our claims alive while admissions are deferred (a
            # full pool must not silently forfeit work to a peer).
            transport.renew(pending)
        while pending and engine.admit_handoff(pending[0]):
            handoff = pending.popleft()
            admits += 1
            if fault is not None and fault.kind == "handoff_crash_preack" \
                    and fault.due(admits):
                # The ack-crash window, deterministically: the handoff
                # is ADMITTED (scattered, recorded) but its claim file
                # survives — redelivery must find the engine's seen-set.
                fault.take()
                raise FaultInjected(
                    f"injected handoff_crash_preack at admit {admits} "
                    f"(uid {handoff.uid} admitted, never acked)")
            transport.ack(handoff)
            if fault is not None and fault.kind == "handoff_dup" \
                    and fault.due(admits):
                # Duplicate-delivery drill: the same payload arrives
                # again (a peer double-claim after lease skew) — queued
                # at the tail so the admit loop meets it as a fresh
                # delivery.
                fault.take()
                pending.append(handoff)
        has_work = engine.pool.any_live()
        if has_work:
            engine.step()
            last_progress = time.time()
        if on_tick is not None:
            on_tick(engine)
        if not has_work:
            if transport.finished() and not pending:
                break
            if idle_timeout_s is not None and not pending \
                    and time.time() - last_progress > idle_timeout_s:
                break                  # producer died sentinel-less
            if idle_wait_s:
                time.sleep(idle_wait_s)
    return engine.completions


def run_disagg(prefill_engine, decode_engine, requests,
               max_ticks: int = 10000
               ) -> Tuple[List[Completion], List[Completion]]:
    """In-process disaggregated run: one prefill engine and one decode
    engine over a :class:`QueueTransport`, ticked in lockstep (each
    engine only when it has work, so the combined tick count is
    comparable with an interleaved baseline's).  Returns
    ``(prefill_completions, decode_completions)``; the caller checks
    conservation (every handoff uid terminates on the decode side)."""
    transport = prefill_engine.handoff_sink.__self__ \
        if hasattr(prefill_engine.handoff_sink, "__self__") else None
    if not isinstance(transport, QueueTransport):
        raise ValueError("run_disagg drives a QueueTransport pair: build "
                         "the prefill engine with handoff_sink="
                         "transport.send")
    prefill_engine.queue.submit_all(requests)
    prefill_engine.queue.close()
    decode_engine.queue.close()
    pending: deque = deque()
    ticks = 0
    while ticks < max_ticks:
        p_active = not (prefill_engine.queue.drained()
                        and not prefill_engine.pool.any_live())
        if p_active:
            prefill_engine.step()
            ticks += 1
        pending.extend(transport.poll())
        while pending and decode_engine.admit_handoff(pending[0]):
            transport.ack(pending.popleft())
        if decode_engine.pool.any_live():
            decode_engine.step()
            ticks += 1
        if not p_active and not pending \
                and not decode_engine.pool.any_live():
            break
    else:
        raise RuntimeError(f"disagg run did not converge within "
                           f"{max_ticks} ticks")
    transport.close()
    return prefill_engine.completions, decode_engine.completions
