"""Disaggregated prefill/decode serving: roles + KV-handoff transport.

The interleaved engine (serve/engine.py, role "both") runs prefill
chunks and decode steps through ONE [SLOTS, block_size]-wide compiled
program: a tick that advances three long prefills and one decoding slot
charges the decoding slot the full chunked-prefill geometry.  The
production-TPU serving shape (the Gemma serving paper, PAPERS.md)
splits the two phases onto separate worker pools instead:

- a **prefill worker** (role "prefill") admits fresh requests, chunk-
  prefills each prompt into its local paged arena, samples the FIRST
  token, then terminates the request locally with status "handoff",
  shipping its KV blocks through a transport;
- a **decode worker** (role "decode") scatters each payload into its
  own arena (``BlockPool.admit_prefilled``) and decodes from there —
  its compiled step is [SLOTS, 1]-wide, so decode ticks stop paying
  for prefill lanes entirely.  TPOT on the decode role beats the
  interleaved baseline because every one of its ticks is the cheap
  program.

The handoff payload (:class:`KvHandoff`) is storage-dtype-exact: int8
arenas ship int8 rows plus their bf16 per-token block scales
(quant/kv.py), full-precision arenas ship full-precision rows.  The
copy is deep by construction — a COW-shared prefix block's bytes are
gathered out of the arena, so refcounts on the prefill side stay
consistent (the shared block parks in the reusable cache at eviction)
and the decode side can never alias it.

Transports:

- :class:`QueueTransport` — in-process deque, what the tier-1
  comparison test and :func:`run_disagg` drive;
- :class:`FileTransport` — a spool directory of ``handoff-*.npz``
  files written atomically (tmp + rename) plus a ``close.json``
  sentinel, connecting a ``serve.py --role prefill`` process to a
  ``--role decode`` process with no shared memory.  Files survive on
  disk until the consumer ACKS them at admission, so a decode worker
  stopped at a --steps cap (or before admitting) leaves its
  unadmitted handoffs re-servable; a worker that dies between ack and
  terminal status still loses those in-flight requests (the fleet
  stratum's exactly-once machinery is the inbox/outbox protocol, not
  this spool — compose them by fronting each role with a router).

Determinism: handoffs are sequence-numbered at send time and admitted
in that order; a payload that exceeds the decode worker's free blocks
is REQUEUED at the head (``admit_handoff`` returns False leaving no
state behind) and retried after evictions free capacity — never
dropped, never a crash.

Both sides emit schema-v12 ``kv_handoff`` records (direction out/in);
``tools/ci_gate.py --disagg-stream`` checks a recorded pair of role
streams for conservation (zero lost handoffs) and
``tools/serve_report.py`` renders the HANDOFF latency line.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from apex_example_tpu.serve.queue import Completion, Request


@dataclass
class KvHandoff:
    """One request's prefilled KV state in flight between roles.

    ``tokens`` is the full token list so far — the prompt plus the
    prefill worker's first sampled token; ``fill`` counts tokens whose
    KV the payload covers (== prompt length); ``payload`` maps each
    arena leaf's path string to a ``[n_blocks, block_size, ...]`` host
    array in the leaf's storage dtype."""

    uid: str
    request: Request
    tokens: List[int]
    fill: int
    block_size: int
    kv_dtype: str
    payload: Dict[str, np.ndarray]
    payload_bytes: int
    t_out_wall: float
    src: str = ""
    requeued: int = 0       # deferred-admission episodes, decode side
    # prefill-side latency trail (wall-independent, for the kv_handoff
    # record): the request's measured TTFT/queue wait up to handoff.
    ttft_ms: Optional[float] = None
    queue_wait_ms: Optional[float] = None
    spool_file: Optional[str] = None   # FileTransport bookkeeping


class QueueTransport:
    """In-process handoff channel: FIFO, closed explicitly by the
    prefill side once its workload is drained."""

    def __init__(self):
        self._q: deque = deque()
        self._closed = False
        self.sent = 0

    def send(self, handoff: KvHandoff) -> None:
        if self._closed:
            raise RuntimeError("transport is closed")
        self.sent += 1
        self._q.append(handoff)

    def poll(self) -> List[KvHandoff]:
        out = list(self._q)
        self._q.clear()
        return out

    def ack(self, handoff: KvHandoff) -> None:
        """Admission consumed the handoff (no-op in process: nothing
        outlives the deque)."""

    def close(self) -> None:
        self._closed = True

    def finished(self) -> bool:
        """No more handoffs will ever arrive (closed and drained)."""
        return self._closed and not self._q


class FileTransport:
    """File-spool handoff channel between role processes.

    The prefill side writes ``handoff-<seq>-<uid>.npz`` (payload arrays
    plus a JSON meta member) via tmp-file + atomic rename, then a
    ``close.json`` sentinel carrying the total count.  The decode side
    polls the directory, loads files in sequence order exactly once and
    deletes them.  Single producer, single consumer."""

    SENTINEL = "close.json"

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._seq = 0
        self.sent = 0
        self._expected: Optional[int] = None
        self._consumed = 0
        self._loaded: set = set()

    def pending_on_disk(self) -> int:
        """Spool files not yet acked — what a stopped decode worker
        leaves behind for the next one (serve.py counts these as
        stranded at a --steps cap)."""
        return sum(1 for n in os.listdir(self.path)
                   if n.startswith("handoff-") and n.endswith(".npz"))

    # ------------------------------------------------------ prefill side

    def send(self, handoff: KvHandoff) -> None:
        name = f"handoff-{self._seq:06d}-{handoff.uid}.npz"
        self._seq += 1
        req = handoff.request
        meta = {
            "uid": handoff.uid,
            "tokens": [int(t) for t in handoff.tokens],
            "fill": handoff.fill,
            "block_size": handoff.block_size,
            "kv_dtype": handoff.kv_dtype,
            "payload_bytes": handoff.payload_bytes,
            "t_out_wall": handoff.t_out_wall,
            "src": handoff.src,
            "keys": list(handoff.payload.keys()),
            "request": {
                "prompt": [int(t) for t in req.prompt],
                "max_new_tokens": req.max_new_tokens,
                "temperature": req.temperature,
                "top_k": req.top_k,
                "eos_id": req.eos_id,
            },
        }
        arrays = {f"a{i}": handoff.payload[k].view(np.uint8)
                  if handoff.payload[k].dtype.kind == "V"
                  else handoff.payload[k]
                  for i, k in enumerate(meta["keys"])}
        # bfloat16 has no portable npz spelling; ship raw bytes plus
        # the dtype names needed to reinterpret on the other side.
        meta["dtypes"] = [str(handoff.payload[k].dtype)
                          for k in meta["keys"]]
        meta["shapes"] = [list(handoff.payload[k].shape)
                          for k in meta["keys"]]
        tmp = os.path.join(self.path, f".tmp-{name}")
        with open(tmp, "wb") as fh:
            np.savez(fh, meta=np.frombuffer(
                json.dumps(meta).encode(), np.uint8), **arrays)
        os.replace(tmp, os.path.join(self.path, name))
        self.sent += 1

    def close(self) -> None:
        tmp = os.path.join(self.path, ".tmp-" + self.SENTINEL)
        with open(tmp, "w") as fh:
            json.dump({"handoffs": self.sent, "time": time.time()}, fh)
        os.replace(tmp, os.path.join(self.path, self.SENTINEL))

    # ------------------------------------------------------- decode side

    def poll(self) -> List[KvHandoff]:
        """Load every not-yet-loaded spool file, in sequence order.
        Files stay ON DISK until the consumer acks them (admission
        succeeded or the handoff terminated) — a decode worker stopped
        at a --steps cap leaves its unadmitted handoffs in the spool,
        re-servable by the next worker, instead of silently discarding
        them.  A torn write is impossible (atomic rename); a broken
        file is a real bug and raises."""
        out = []
        names = sorted(n for n in os.listdir(self.path)
                       if n.startswith("handoff-") and n.endswith(".npz")
                       and n not in self._loaded)
        for name in names:
            out.append(self._load(os.path.join(self.path, name)))
            out[-1].spool_file = name
            self._loaded.add(name)
        return out

    def ack(self, handoff: KvHandoff) -> None:
        """The consumer owns the handoff now (admitted or terminally
        rejected): drop its spool file."""
        name = handoff.spool_file
        if name:
            try:
                os.remove(os.path.join(self.path, name))
            except FileNotFoundError:
                pass
            handoff.spool_file = None
        self._consumed += 1

    def _load(self, full: str) -> KvHandoff:
        import ml_dtypes  # noqa: F401  (registers bfloat16 with numpy)
        with np.load(full) as z:
            meta = json.loads(bytes(z["meta"]).decode())
            payload = {}
            for i, key in enumerate(meta["keys"]):
                arr = z[f"a{i}"]
                want = np.dtype(meta["dtypes"][i])
                if arr.dtype != want:
                    arr = arr.view(want)
                payload[key] = arr.reshape(meta["shapes"][i])
        spec = meta["request"]
        req = Request(prompt=spec["prompt"],
                      max_new_tokens=int(spec["max_new_tokens"]),
                      temperature=float(spec.get("temperature", 0.0)),
                      top_k=int(spec.get("top_k", 0)),
                      eos_id=spec.get("eos_id"),
                      uid=meta["uid"])
        return KvHandoff(
            uid=meta["uid"], request=req, tokens=meta["tokens"],
            fill=int(meta["fill"]), block_size=int(meta["block_size"]),
            kv_dtype=meta["kv_dtype"],
            payload=payload,
            payload_bytes=int(meta["payload_bytes"]),
            t_out_wall=float(meta["t_out_wall"]),
            src=meta.get("src", ""))

    def finished(self) -> bool:
        sentinel = os.path.join(self.path, self.SENTINEL)
        if self._expected is None and os.path.exists(sentinel):
            with open(sentinel) as fh:
                self._expected = int(json.load(fh)["handoffs"])
        return self._expected is not None \
            and self._consumed >= self._expected


# ------------------------------------------------------------ drive loops


def run_prefill_role(engine, transport, max_steps: Optional[int] = None,
                     idle_wait_s: float = 0.0, stop=None,
                     on_tick=None) -> List[Completion]:
    """Drive a prefill-role engine over its (already submitted) queue,
    then close the transport — the decode side's end-of-stream signal.
    The engine itself ships each handoff at first-token time
    (``handoff_sink`` is the transport's ``send``)."""
    comps = engine.run(max_steps=max_steps, idle_wait_s=idle_wait_s,
                       stop=stop, on_tick=on_tick)
    transport.close()
    return comps


def run_decode_role(engine, transport, max_steps: Optional[int] = None,
                    idle_wait_s: float = 0.0, stop=None,
                    on_tick=None) -> List[Completion]:
    """Drive a decode-role engine off a transport: poll for handoffs,
    admit them IN ORDER (a handoff the pool cannot fit yet stays at the
    head and is retried next tick — deterministic requeue, never a
    drop), tick while there is work, exit once the transport is
    finished and every admitted request terminated."""
    engine.queue.close()               # decode-role intake is the transport
    pending: deque = deque()
    while max_steps is None or engine.step_count < max_steps:
        if stop is not None and stop():
            break
        pending.extend(transport.poll())
        while pending and engine.admit_handoff(pending[0]):
            transport.ack(pending.popleft())
        has_work = engine.pool.any_live()
        if has_work:
            engine.step()
        if on_tick is not None:
            on_tick(engine)
        if not has_work:
            if transport.finished() and not pending:
                break
            if idle_wait_s:
                time.sleep(idle_wait_s)
    return engine.completions


def run_disagg(prefill_engine, decode_engine, requests,
               max_ticks: int = 10000
               ) -> Tuple[List[Completion], List[Completion]]:
    """In-process disaggregated run: one prefill engine and one decode
    engine over a :class:`QueueTransport`, ticked in lockstep (each
    engine only when it has work, so the combined tick count is
    comparable with an interleaved baseline's).  Returns
    ``(prefill_completions, decode_completions)``; the caller checks
    conservation (every handoff uid terminates on the decode side)."""
    transport = prefill_engine.handoff_sink.__self__ \
        if hasattr(prefill_engine.handoff_sink, "__self__") else None
    if not isinstance(transport, QueueTransport):
        raise ValueError("run_disagg drives a QueueTransport pair: build "
                         "the prefill engine with handoff_sink="
                         "transport.send")
    prefill_engine.queue.submit_all(requests)
    prefill_engine.queue.close()
    decode_engine.queue.close()
    pending: deque = deque()
    ticks = 0
    while ticks < max_ticks:
        p_active = not (prefill_engine.queue.drained()
                        and not prefill_engine.pool.any_live())
        if p_active:
            prefill_engine.step()
            ticks += 1
        pending.extend(transport.poll())
        while pending and decode_engine.admit_handoff(pending[0]):
            transport.ack(pending.popleft())
        if decode_engine.pool.any_live():
            decode_engine.step()
            ticks += 1
        if not p_active and not pending \
                and not decode_engine.pool.any_live():
            break
    else:
        raise RuntimeError(f"disagg run did not converge within "
                           f"{max_ticks} ticks")
    transport.close()
    return prefill_engine.completions, decode_engine.completions
