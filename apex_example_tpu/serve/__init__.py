"""apex_example_tpu.serve — continuous-batching inference.

The serving counterpart of the training engine: a slot pool over one
shared per-layer KV cache (``serve/slots.py``), a scheduler loop that
advances every live request with ONE compiled decode step per tick
(``serve/engine.py``), a thread-safe request queue with the timestamp
trail TTFT/TPOT metrics derive from (``serve/queue.py``), and a
deterministic synthetic load generator (``serve/loadgen.py``).

``serve.py`` at the repo root is the CLI driver (checkpoint restore or
random init, synthetic stream, schema-v3 JSONL serving records);
``tools/serve_report.py`` is the jax-free summary client.
"""

from apex_example_tpu.serve.engine import (ServeEngine,
                                           request_complete_record)
from apex_example_tpu.serve.loadgen import parse_range, synthetic_requests
from apex_example_tpu.serve.queue import Completion, Request, RequestQueue
from apex_example_tpu.serve.slots import Slot, SlotPool

__all__ = [
    "Completion", "Request", "RequestQueue", "ServeEngine", "Slot",
    "SlotPool", "parse_range", "request_complete_record",
    "synthetic_requests",
]
