"""apex_example_tpu.serve — continuous-batching inference.

The serving counterpart of the training engine: a BLOCK-PAGED KV cache
— per-layer arenas + free-list allocator + per-slot block tables with
copy-on-write prefix sharing (``serve/slots.py``) — a scheduler loop
that advances every live request with ONE compiled decode step per
tick, chunked prefill included (``serve/engine.py``), a thread-safe
request queue with the timestamp trail TTFT/TPOT metrics derive from
(``serve/queue.py``), and a deterministic synthetic load generator
with a shared-system-prompt mode (``serve/loadgen.py``).

The resilience layer (ISSUE 5) rides the same modules: per-request
deadlines/TTL (queued-expire and mid-flight evict), bounded admission
with deterministic load shedding (``RequestQueue(max_pending=...)``),
cancellation, slot-level failure isolation with a degenerate-token
guard, and graceful drain (``ServeEngine.drain``) — every request
terminates in a first-class ``Completion(status=...)``.

Sharded + disaggregated serving (ISSUE 14): under a registered
parallel_state mesh the engine TP-shards the weights and per-layer KV
arenas over heads on the ``model`` axis (block tables and admission
stay host-side and replicated), and ``serve/disagg.py`` splits prefill
and decode into separate roles connected by a KV-block handoff
transport — long prompts stop stalling decode ticks.  The file spool
is CRASH-SAFE (ISSUE 15): leased claims by atomic rename,
ack-by-delete at admission, redelivery of a dead worker's claims via
lease reclaim or own-claim adoption, idempotent admission on handoff
uid (the engine's seen-set duplicate-acks the ack-crash window), and
quarantine for corrupt payloads — N decode workers per spool.

``serve.py`` at the repo root is the CLI driver (checkpoint restore or
random init, synthetic stream, schema-v5 JSONL serving records, SIGTERM
drain-to-EX_TEMPFAIL, ``--inject-fault`` drills, ``--mesh dp,tp`` and
``--role prefill|decode|both``);
``tools/serve_report.py`` is the jax-free summary client.
"""

from apex_example_tpu.serve.disagg import (FileTransport, KvHandoff,
                                           QueueTransport,
                                           run_decode_role, run_disagg,
                                           run_prefill_role)
from apex_example_tpu.serve.engine import (ServeEngine, SlotFailure,
                                           request_complete_record,
                                           request_failed_record)
from apex_example_tpu.serve.loadgen import (parse_range, substream,
                                            synthetic_requests,
                                            tenant_requests)
from apex_example_tpu.serve.queue import (STATUSES, Completion, Request,
                                          RequestQueue)
from apex_example_tpu.serve.slots import BlockAllocator, BlockPool, Slot

__all__ = [
    "BlockAllocator", "BlockPool", "Completion", "FileTransport",
    "KvHandoff", "QueueTransport", "Request",
    "RequestQueue", "STATUSES", "ServeEngine", "Slot", "SlotFailure",
    "parse_range", "request_complete_record", "request_failed_record",
    "run_decode_role", "run_disagg", "run_prefill_role", "substream",
    "synthetic_requests", "tenant_requests",
]
