"""apex_example_tpu.serve — continuous-batching inference.

The serving counterpart of the training engine: a slot pool over one
shared per-layer KV cache (``serve/slots.py``), a scheduler loop that
advances every live request with ONE compiled decode step per tick
(``serve/engine.py``), a thread-safe request queue with the timestamp
trail TTFT/TPOT metrics derive from (``serve/queue.py``), and a
deterministic synthetic load generator (``serve/loadgen.py``).

The resilience layer (ISSUE 5) rides the same modules: per-request
deadlines/TTL (queued-expire and mid-flight evict), bounded admission
with deterministic load shedding (``RequestQueue(max_pending=...)``),
cancellation, slot-level failure isolation with a degenerate-token
guard, and graceful drain (``ServeEngine.drain``) — every request
terminates in a first-class ``Completion(status=...)``.

``serve.py`` at the repo root is the CLI driver (checkpoint restore or
random init, synthetic stream, schema-v5 JSONL serving records, SIGTERM
drain-to-EX_TEMPFAIL, ``--inject-fault`` drills);
``tools/serve_report.py`` is the jax-free summary client.
"""

from apex_example_tpu.serve.engine import (ServeEngine, SlotFailure,
                                           request_complete_record,
                                           request_failed_record)
from apex_example_tpu.serve.loadgen import parse_range, synthetic_requests
from apex_example_tpu.serve.queue import (STATUSES, Completion, Request,
                                          RequestQueue)
from apex_example_tpu.serve.slots import Slot, SlotPool

__all__ = [
    "Completion", "Request", "RequestQueue", "STATUSES", "ServeEngine",
    "Slot", "SlotFailure", "SlotPool", "parse_range",
    "request_complete_record", "request_failed_record",
    "synthetic_requests",
]
