"""Request queue + request/completion records for the serving engine.

Pure host-side bookkeeping (no jax import): the engine thread pops
admissible requests, the load generator (or any producer thread) submits
them.  Every latency metric the serving stack reports — TTFT, TPOT, queue
wait — is derived from the four timestamps a request accumulates on its
way through (arrival, admission, first token, completion), so they live
here next to the dataclasses rather than in the engine.

Arrival gating supports two clocks:

- wall clock (the serving default): a producer thread submits when the
  request "arrives"; the engine admits whatever is in the queue.
- virtual step time (``arrival_step``): the request is submitted up
  front but becomes admissible only once the engine's step counter
  reaches ``arrival_step``.  Deterministic staggered arrivals — what the
  tier-1 continuous-batching test pins (tests/test_serve.py).

Request deadlines mirror the two clocks: ``deadline_s`` is a wall-clock
TTL from arrival (the production knob), ``deadline_step`` an absolute
engine tick by which the request must have finished (the deterministic
testing knob — no wall-clock sleeps needed to exercise the timeout
path).  Both are honored while queued (expire without admitting) AND
while decoding (the engine evicts the slot mid-flight).

Admission control: ``max_pending`` bounds the ARRIVED backlog — requests
whose gate has passed (or that never had one).  Future-gated requests
don't count; they haven't arrived yet.  When an arrival pushes the
backlog past the bound the overflow is shed deterministically
(``shed_policy``: "newest" rejects the most recently submitted arrivals,
the default; "oldest" drops the head so fresh traffic wins).  Shedding
happens at arrival evaluation inside the engine tick, so the engine owns
the ``shed`` records and Completions.

Every request terminates in a first-class :class:`Completion` whose
``status`` is one of ``ok`` / ``timeout`` / ``shed`` / ``cancelled`` /
``failed`` / ``drained`` / ``rejected`` — the serving stack never loses
a request silently (ISSUE 5; ``rejected`` is the admission-time verdict
for requests the engine could never serve, ISSUE 8).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

_uid = itertools.count()

# Terminal request statuses (Completion.status).  "ok" is the only
# success; "drained" means the request was never admitted before a
# graceful drain and was handed back for requeueing on another replica;
# "rejected" means admission determined the request can NEVER be served
# by this engine (prompt fills the whole cache so the output budget is
# zero, or the worst-case block need exceeds the arena) — terminated
# first-class at admission instead of occupying a slot to emit nothing;
# "handoff" means a prefill-role engine finished the prompt, sampled
# the first token and shipped the request's KV blocks to a decode
# worker (serve/disagg.py) — like "drained", the request continues
# elsewhere, so it sits outside the availability denominator.
# "migrated" (ISSUE 20) is the live-migration counterpart: a MID-FLIGHT
# request whose KV blocks, generated tokens and sampler state were
# snapshotted (ServeEngine.extract_live) and shipped to a peer that
# resumes it token-identically — again outside the availability
# denominator (the destination owns the terminal).
STATUSES = ("ok", "timeout", "shed", "cancelled", "failed", "drained",
            "rejected", "handoff", "migrated")


def _next_uid() -> str:
    return f"req-{next(_uid):06d}"


@dataclass
class Request:
    """One generation request.  ``prompt`` is a token-id list; sampling is
    per-request (temperature 0 = greedy, top_k 0 = full softmax) — the
    engine batches mixed sampling configs in one compiled step."""

    prompt: Sequence[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    eos_id: Optional[int] = None
    # Multi-tenant identity (ISSUE 19): which lane the fair scheduler
    # files this under, and an intra-lane priority bump (higher admits
    # first within the tenant, stable among equals).  Defaults keep
    # legacy single-tenant construction — and its emitted records —
    # byte-identical.
    tenant: str = "default"
    priority: int = 0
    uid: str = field(default_factory=_next_uid)
    # Virtual-time admission gate (None = admissible immediately).
    arrival_step: Optional[int] = None
    # Deadlines: wall-clock TTL from arrival, and/or an absolute engine
    # tick by which the request must have COMPLETED (at tick >=
    # deadline_step an unfinished request is expired — queued or
    # decoding).  Either, both, or neither may be set.
    deadline_s: Optional[float] = None
    deadline_step: Optional[int] = None
    # Wall-clock arrival.  For ungated requests this is submission time;
    # for arrival_step-gated ones RequestQueue.mature() RE-STAMPS it at
    # the tick the gate passes — the request "arrives" then, and TTFT /
    # queue-wait must not charge the virtual pre-arrival wait to the
    # engine (the load generator builds all requests up front).
    t_arrival: float = field(default_factory=time.perf_counter)
    # Client-side submission stamp (perf_counter), set by the producer
    # that BUILT the request (serve/loadgen.py) before it reached the
    # queue: the loadgen->queue handoff then shows as its own "submit"
    # span on a --trace timeline instead of folding into queue wait.
    # None (the default) means the request was built at submission.
    t_submit: Optional[float] = None
    _arrival_stamped: bool = field(default=False, repr=False)

    def __post_init__(self):
        if len(self.prompt) < 1:
            raise ValueError(f"{self.uid}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(f"{self.uid}: max_new_tokens must be >= 1")
        if self.temperature < 0:
            raise ValueError(f"{self.uid}: temperature must be >= 0")
        if self.top_k < 0:
            raise ValueError(f"{self.uid}: top_k must be >= 0")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError(f"{self.uid}: deadline_s must be > 0")
        if self.deadline_step is not None and self.deadline_step < 1:
            raise ValueError(f"{self.uid}: deadline_step must be >= 1")

    def arrived(self, step: int) -> bool:
        """Has this request arrived by engine tick ``step``?"""
        return self.arrival_step is None or self.arrival_step <= step

    def expired(self, step: int, now: float) -> bool:
        """Deadline check, on either clock.  Only meaningful once the
        request has arrived (the engine never asks earlier)."""
        if self.deadline_step is not None and step >= self.deadline_step:
            return True
        if self.deadline_s is not None \
                and now - self.t_arrival > self.deadline_s:
            return True
        return False


@dataclass
class Completion:
    """A terminated request: its status, the generated tokens (prompt
    excluded — possibly partial, possibly empty for never-admitted
    requests) plus the slot/step/timestamp trail the serving metrics are
    computed from.

    ``status`` "ok" keeps the original contract (``finish_reason`` is
    "eos" or "length", all timestamps set).  Non-success statuses use
    ``finish_reason == status``; a request that never reached a slot has
    ``slot == -1`` and ``t_admitted``/``t_first_token`` None.
    """

    request: Request
    tokens: List[int]
    finish_reason: str          # "eos" | "length" | a non-ok status
    slot: int
    admitted_step: int
    finished_step: int
    t_admitted: Optional[float]
    t_first_token: Optional[float]
    t_finish: float
    status: str = "ok"
    error: Optional[str] = None  # traceback digest for status "failed"

    @property
    def ttft_s(self) -> Optional[float]:
        """Time to first token, measured from ARRIVAL (queue wait is part
        of the latency a caller sees).  None before/without a first
        token (shed, queued-timeout, drained)."""
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.request.t_arrival

    @property
    def tpot_s(self) -> float:
        """Time per output token after the first (0 for <=1-token
        outputs)."""
        n = len(self.tokens)
        if n <= 1 or self.t_first_token is None:
            return 0.0
        return (self.t_finish - self.t_first_token) / (n - 1)

    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.t_admitted is None:
            return None
        return self.t_admitted - self.request.t_arrival

    @property
    def e2e_s(self) -> float:
        return self.t_finish - self.request.t_arrival


class RequestQueue:
    """Thread-safe FIFO with virtual-time admission gating, an optional
    pending bound (admission control) and deadline bookkeeping.

    ``pop(step)`` returns the head request if it is admissible at engine
    step ``step`` (its ``arrival_step`` gate has passed), else None —
    FIFO order is preserved: a gated head blocks later requests even if
    their gates passed, matching a real ingress queue.

    ``max_pending`` bounds the arrived backlog; the engine calls
    ``shed_overflow(step)`` once per tick (after ``mature``) and owns the
    records for whatever comes back.  ``expire(step, now)`` returns
    arrived-but-unadmitted requests whose deadline passed — expired
    without ever occupying a slot.
    """

    def __init__(self, max_pending: Optional[int] = None,
                 shed_policy: str = "newest"):
        if max_pending is not None and max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {max_pending}")
        if shed_policy not in ("newest", "oldest"):
            raise ValueError(f"shed_policy must be 'newest' or 'oldest', "
                             f"got {shed_policy!r}")
        self.max_pending = max_pending
        self.shed_policy = shed_policy
        self._q: deque = deque()                # guarded-by: _lock
        self._lock = threading.Lock()
        self._closed = False                    # guarded-by: _lock
        # Sticky: set once any deadline-carrying request is submitted,
        # so the per-tick expire() scan is skipped entirely on the
        # (default) deadline-free path — a 20k-request backlog must not
        # pay an O(n) no-op scan under the lock every engine tick.
        self._has_deadlines = False             # guarded-by: _lock

    def submit(self, request: Request) -> None:
        with self._lock:
            if self._closed:
                raise RuntimeError("queue is closed")
            if request.deadline_s is not None \
                    or request.deadline_step is not None:
                self._has_deadlines = True
            # An ungated request "arrives" NOW — at submission, as the
            # t_arrival docstring has always said — not at whatever
            # earlier moment the dataclass was constructed: the
            # build->submit gap is the client's (the "submit" span on
            # a --trace timeline, when t_submit is stamped), and queue
            # wait must not absorb it.  Gated requests re-stamp at
            # their virtual gate instead (mature()).
            if request.arrival_step is None \
                    and not request._arrival_stamped:
                request.t_arrival = time.perf_counter()
                request._arrival_stamped = True
            self._q.append(request)

    def submit_all(self, requests) -> None:
        for r in requests:
            self.submit(r)

    def mature(self, step: int) -> None:
        """Stamp wall-clock arrival on every gated request whose
        ``arrival_step`` has been reached at engine tick ``step`` — even
        the ones not yet poppable (all slots busy): time spent waiting
        AFTER the gate passes is genuine queue wait and must count.
        ``t_submit`` is re-stamped with it: a virtually-gated request
        was built up front by the load generator, so the build->gate
        delay is deliberate staggering, not client handoff — charging
        it to a "submit" span would reintroduce under a new name the
        exact pre-arrival wait this re-stamp exists to exclude (real
        handoff survives only on ungated, wall-clock submissions).
        The engine calls this once per tick, before admission."""
        now = time.perf_counter()
        with self._lock:
            for req in self._q:
                if (req.arrival_step is not None and not
                        req._arrival_stamped and req.arrival_step <= step):
                    req.t_arrival = now
                    if req.t_submit is not None:
                        req.t_submit = now
                    req._arrival_stamped = True

    def shed_overflow(self, step: int) -> List[Request]:
        """Admission control: requests shed because the arrived backlog
        exceeds ``max_pending`` at tick ``step``.  Deterministic —
        "newest" rejects the latest arrivals (back of the queue),
        "oldest" drops the head.  No-op without a bound."""
        if self.max_pending is None:
            return []
        with self._lock:
            if len(self._q) <= self.max_pending:
                return []              # O(1): arrived <= total <= bound
            arrived = [i for i, r in enumerate(self._q) if r.arrived(step)]
            excess = len(arrived) - self.max_pending
            if excess <= 0:
                return []
            victims = set(arrived[-excess:] if self.shed_policy == "newest"
                          else arrived[:excess])
            shed = [r for i, r in enumerate(self._q) if i in victims]
            self._q = deque(r for i, r in enumerate(self._q)
                            if i not in victims)
            return shed

    def expire(self, step: int, now: float) -> List[Request]:
        """Arrived-but-unadmitted requests whose deadline has passed at
        tick ``step`` — removed and returned so the engine can terminate
        them with status "timeout" without ever admitting them."""
        with self._lock:
            # Sticky-flag fast path INSIDE the lock: the flag is set by
            # producer threads (submit) and read here by the engine —
            # graftlint's lock-discipline rule caught the original
            # unguarded read (ISSUE 9).  The O(n) scan is still skipped
            # on the deadline-free path; the uncontended acquire is the
            # whole cost.
            if not self._has_deadlines:
                return []
            dead = [r for r in self._q
                    if r.arrived(step) and r.expired(step, now)]
            if dead:
                gone = set(id(r) for r in dead)
                self._q = deque(r for r in self._q if id(r) not in gone)
            return dead

    def cancel(self, uid: str) -> Optional[Request]:
        """Remove a queued request by uid (None if not queued — it may
        already be decoding; the engine handles that side)."""
        with self._lock:
            for r in self._q:
                if r.uid == uid:
                    self._q.remove(r)
                    return r
            return None

    def pop(self, step: int) -> Optional[Request]:
        with self._lock:
            if not self._q:
                return None
            head = self._q[0]
            if head.arrival_step is not None and head.arrival_step > step:
                return None
            return self._q.popleft()

    def push_front(self, request: Request) -> None:
        """Hand a popped request back to the HEAD of the queue — the
        engine's deterministic out-of-blocks queueing (head-of-line:
        FIFO order is preserved while the head waits for KV blocks).
        Allowed on a closed queue: this is the engine returning work it
        already owns, not a new submission."""
        with self._lock:
            self._q.appendleft(request)

    def pending(self) -> int:
        with self._lock:
            return len(self._q)

    def arrived_pending(self, step: int) -> int:
        """The ARRIVED backlog at tick ``step`` — what ``max_pending``
        bounds (future-gated requests are queued but have not arrived,
        so they must not be reported against the bound)."""
        with self._lock:
            return sum(1 for r in self._q if r.arrived(step))

    def close(self) -> None:
        """No more submissions; the engine drains what is queued and
        exits its loop when the queue is empty and every slot is free."""
        with self._lock:
            self._closed = True

    def drain(self) -> List[Request]:
        """Graceful-drain takeover: close the queue and hand back every
        still-queued request (admitted requests are the engine's to
        finish or deadline-evict).  The caller requeues them elsewhere —
        status "drained", not lost."""
        with self._lock:
            self._closed = True
            out = list(self._q)
            self._q.clear()
            return out

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def drained(self) -> bool:
        with self._lock:
            return self._closed and not self._q
