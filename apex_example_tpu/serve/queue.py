"""Request queue + request/completion records for the serving engine.

Pure host-side bookkeeping (no jax import): the engine thread pops
admissible requests, the load generator (or any producer thread) submits
them.  Every latency metric the serving stack reports — TTFT, TPOT, queue
wait — is derived from the four timestamps a request accumulates on its
way through (arrival, admission, first token, completion), so they live
here next to the dataclasses rather than in the engine.

Arrival gating supports two clocks:

- wall clock (the serving default): a producer thread submits when the
  request "arrives"; the engine admits whatever is in the queue.
- virtual step time (``arrival_step``): the request is submitted up
  front but becomes admissible only once the engine's step counter
  reaches ``arrival_step``.  Deterministic staggered arrivals — what the
  tier-1 continuous-batching test pins (tests/test_serve.py).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

_uid = itertools.count()


def _next_uid() -> str:
    return f"req-{next(_uid):06d}"


@dataclass
class Request:
    """One generation request.  ``prompt`` is a token-id list; sampling is
    per-request (temperature 0 = greedy, top_k 0 = full softmax) — the
    engine batches mixed sampling configs in one compiled step."""

    prompt: Sequence[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    eos_id: Optional[int] = None
    uid: str = field(default_factory=_next_uid)
    # Virtual-time admission gate (None = admissible immediately).
    arrival_step: Optional[int] = None
    # Wall-clock arrival.  For ungated requests this is submission time;
    # for arrival_step-gated ones RequestQueue.mature() RE-STAMPS it at
    # the tick the gate passes — the request "arrives" then, and TTFT /
    # queue-wait must not charge the virtual pre-arrival wait to the
    # engine (the load generator builds all requests up front).
    t_arrival: float = field(default_factory=time.perf_counter)
    _arrival_stamped: bool = field(default=False, repr=False)

    def __post_init__(self):
        if len(self.prompt) < 1:
            raise ValueError(f"{self.uid}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(f"{self.uid}: max_new_tokens must be >= 1")
        if self.temperature < 0:
            raise ValueError(f"{self.uid}: temperature must be >= 0")
        if self.top_k < 0:
            raise ValueError(f"{self.uid}: top_k must be >= 0")


@dataclass
class Completion:
    """A finished request: the generated tokens (prompt excluded) plus the
    slot/step/timestamp trail the serving metrics are computed from."""

    request: Request
    tokens: List[int]
    finish_reason: str          # "eos" | "length"
    slot: int
    admitted_step: int
    finished_step: int
    t_admitted: float
    t_first_token: float
    t_finish: float

    @property
    def ttft_s(self) -> float:
        """Time to first token, measured from ARRIVAL (queue wait is part
        of the latency a caller sees)."""
        return self.t_first_token - self.request.t_arrival

    @property
    def tpot_s(self) -> float:
        """Time per output token after the first (0 for 1-token outputs)."""
        n = len(self.tokens)
        if n <= 1:
            return 0.0
        return (self.t_finish - self.t_first_token) / (n - 1)

    @property
    def queue_wait_s(self) -> float:
        return self.t_admitted - self.request.t_arrival

    @property
    def e2e_s(self) -> float:
        return self.t_finish - self.request.t_arrival


class RequestQueue:
    """Thread-safe FIFO with virtual-time admission gating.

    ``pop(step)`` returns the head request if it is admissible at engine
    step ``step`` (its ``arrival_step`` gate has passed), else None —
    FIFO order is preserved: a gated head blocks later requests even if
    their gates passed, matching a real ingress queue.
    """

    def __init__(self):
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._closed = False

    def submit(self, request: Request) -> None:
        with self._lock:
            if self._closed:
                raise RuntimeError("queue is closed")
            self._q.append(request)

    def submit_all(self, requests) -> None:
        for r in requests:
            self.submit(r)

    def mature(self, step: int) -> None:
        """Stamp wall-clock arrival on every gated request whose
        ``arrival_step`` has been reached at engine tick ``step`` — even
        the ones not yet poppable (all slots busy): time spent waiting
        AFTER the gate passes is genuine queue wait and must count.
        The engine calls this once per tick, before admission."""
        now = time.perf_counter()
        with self._lock:
            for req in self._q:
                if (req.arrival_step is not None and not
                        req._arrival_stamped and req.arrival_step <= step):
                    req.t_arrival = now
                    req._arrival_stamped = True

    def pop(self, step: int) -> Optional[Request]:
        with self._lock:
            if not self._q:
                return None
            head = self._q[0]
            if head.arrival_step is not None and head.arrival_step > step:
                return None
            return self._q.popleft()

    def pending(self) -> int:
        with self._lock:
            return len(self._q)

    def close(self) -> None:
        """No more submissions; the engine drains what is queued and
        exits its loop when the queue is empty and every slot is free."""
        with self._lock:
            self._closed = True

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def drained(self) -> bool:
        with self._lock:
            return self._closed and not self._q
