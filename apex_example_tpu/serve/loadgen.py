"""Synthetic request stream for the serving engine.

Deterministic under a seed: prompt contents, lengths, output budgets and
arrival staggering all come from one RandomState, so a serving run is
reproducible end-to-end (the checkpoint→serve round-trip test and the
CLI's --seed rely on this).

Arrivals are expressed in VIRTUAL engine steps (``Request.arrival_step``)
— the engine's admission gate compares against its tick counter, which
makes "staggered arrivals" deterministic regardless of host speed.  A
wall-clock producer thread can instead submit these same requests late
and leave ``arrival_step`` None.

Overload is deterministic too: ``burst`` groups arrivals — requests
land ``burst`` at a time every ``stagger`` ticks, so a burst sized past
``num_slots + max_pending`` reproducibly exercises the shed path, and
``deadline_steps`` gives every request a virtual-step deadline
(``arrival + deadline_steps``) so the timeout path needs no wall-clock
sleeps (ISSUE 5).
"""

from __future__ import annotations

import time
import zlib
from typing import List, Optional, Tuple

import numpy as np

from apex_example_tpu.serve.queue import Request


def substream(seed: int, index: int) -> int:
    """Derive the ``index``-th independent seed from a base ``seed``.

    The fleet bugfix (ISSUE 12): N replicas handed the same ``--seed``
    drew from ONE seed stream and served IDENTICAL prompt sets — a
    "fleet" workload that was really one workload N times.  Replica i
    now derives ``substream(seed, i)``: disjoint with overwhelming
    probability across indices, yet a pure function of (seed, index),
    so fleet workloads stay exactly reproducible.  ``index`` 0 is NOT
    the identity on purpose — a one-replica substreamed run must not
    silently alias the un-substreamed workload for a different reason
    than replica 1 differs from it.  stdlib-only (crc32), so jax-free
    consumers can mirror the derivation."""
    if index < 0:
        raise ValueError(f"substream index must be >= 0, got {index}")
    return zlib.crc32(f"{seed}/{index}".encode()) & 0x7FFFFFFF


def synthetic_requests(n: int, *, vocab_size: int, seed: int = 0,
                       prompt_len: Tuple[int, int] = (4, 12),
                       max_new: Tuple[int, int] = (4, 16),
                       temperature: float = 0.0, top_k: int = 0,
                       eos_id: Optional[int] = None,
                       stagger: int = 0, burst: int = 1,
                       deadline_steps: Optional[int] = None,
                       deadline_s: Optional[float] = None,
                       shared_prefix: int = 0,
                       seed_substream: Optional[int] = None,
                       repetitive: bool = False
                       ) -> List[Request]:
    """``n`` requests with uniform prompt/output lengths in the given
    inclusive ranges; request i arrives at virtual step
    ``(i // burst) * stagger`` (stagger 0 = all at once; burst b = b
    arrivals per wave — the deterministic overload mode).  With
    ``deadline_steps`` each request must finish within that many engine
    ticks of its arrival; ``deadline_s`` is the wall-clock TTL.

    ``shared_prefix`` > 0 prepends one common N-token "system prompt"
    (drawn once from the same RandomState) to every request's sampled
    prompt — the workload mode that makes the paged KV cache's
    copy-on-write prefix sharing measurable: the common blocks are
    computed once and refcounted across requests (ISSUE 8;
    ``prompt_len`` still sizes only the per-request sampled part).

    ``seed_substream`` (fleet mode, ISSUE 12): replica index i derives
    its RandomState from ``substream(seed, i)`` instead of ``seed``
    directly, so N replicas sharing one base seed serve DISJOINT yet
    individually-deterministic workloads (``--seed-substream`` on
    serve.py).

    ``repetitive`` (ISSUE 18): templated prompts with self-repeating
    spans — each request draws a short motif (3–6 tokens) from the same
    RandomState and tiles it to the sampled prompt length, the
    structured traffic shape (boilerplate templates, copy-through
    fields) that makes prompt-lookup speculative drafting measurable.
    Same substream machinery, so ``--repetitive`` workloads are exactly
    as deterministic per (seed, substream) as the uniform ones."""
    if n < 1:
        raise ValueError(f"need n >= 1 requests, got {n}")
    if prompt_len[0] < 1 or prompt_len[0] > prompt_len[1]:
        raise ValueError(f"bad prompt_len range {prompt_len}")
    if max_new[0] < 1 or max_new[0] > max_new[1]:
        raise ValueError(f"bad max_new range {max_new}")
    if burst < 1:
        raise ValueError(f"burst must be >= 1, got {burst}")
    if deadline_steps is not None and deadline_steps < 1:
        raise ValueError(f"deadline_steps must be >= 1, got "
                         f"{deadline_steps}")
    if shared_prefix < 0:
        raise ValueError(f"shared_prefix must be >= 0, got "
                         f"{shared_prefix}")
    rs = np.random.RandomState(seed if seed_substream is None
                               else substream(seed, seed_substream))
    prefix = rs.randint(0, vocab_size, size=(shared_prefix,)).tolist() \
        if shared_prefix else []
    out = []
    for i in range(n):
        p = int(rs.randint(prompt_len[0], prompt_len[1] + 1))
        m = int(rs.randint(max_new[0], max_new[1] + 1))
        if repetitive:
            motif_len = int(rs.randint(3, 7))
            motif = rs.randint(0, vocab_size,
                               size=(motif_len,)).tolist()
            reps = -(-p // motif_len)           # ceil division
            body = (motif * reps)[:p]
        else:
            body = rs.randint(0, vocab_size, size=(p,)).tolist()
        prompt = prefix + body
        arrival = (i // burst) * stagger if stagger else None
        # Client-side submission stamp: the request is BUILT here, then
        # handed to the queue — a --trace timeline renders the
        # loadgen->queue handoff as its own span (Request.t_submit).
        # For arrival_step-gated requests RequestQueue.mature()
        # re-stamps BOTH clocks at the virtual gate (the build->gate
        # delay is deliberate staggering, not handoff), so a real
        # submit span survives only on ungated wall-clock submissions.
        out.append(Request(prompt=prompt, max_new_tokens=m,
                           temperature=temperature, top_k=top_k,
                           eos_id=eos_id,
                           arrival_step=arrival,
                           deadline_step=(arrival or 0) + deadline_steps
                           if deadline_steps is not None else None,
                           deadline_s=deadline_s,
                           t_submit=time.perf_counter()))
    return out


def tenant_requests(n: int, specs, *, vocab_size: int, seed: int = 0,
                    prompt_len: Tuple[int, int] = (4, 12),
                    max_new: Tuple[int, int] = (4, 16),
                    temperature: float = 0.0, top_k: int = 0,
                    eos_id: Optional[int] = None,
                    stagger: int = 0,
                    deadline_steps: Optional[int] = None,
                    deadline_s: Optional[float] = None,
                    seed_substream: Optional[int] = None,
                    repetitive: bool = False) -> List[Request]:
    """Multi-tenant workload (ISSUE 19): ``n`` total requests split
    across the ``--tenants`` specs proportionally to each tenant's
    ``mix`` (largest-remainder apportionment — deterministic, sums to
    ``n``, every tenant with mix > 0 gets at least one request when
    n >= len(specs)).

    Tenant i draws from ``substream(seed, i)`` (i = spec order), so
    per-tenant streams are DISJOINT yet individually deterministic —
    the same derivation replicas use for fleet workloads, composed:
    under ``seed_substream`` (replica r) tenant i draws from
    ``substream(substream(seed, r), i)``, keeping tenants disjoint
    across replicas too.  ``shared_prefix`` becomes PER-TENANT: each
    tenant's spec-declared prefix length draws from its own substream,
    so prefix-heavy traffic has a distinct warm set per tenant
    (prefix_affinity routing has something to route ON).  ``burst`` is
    per-tenant as well; arrivals from all tenants merge stably by
    arrival step (ties keep spec order).

    ``specs`` is an ordered name -> spec map; specs are duck-typed
    (``mix`` / ``burst`` / ``shared_prefix`` attributes, as on
    sched/tenants.py TenantSpec)."""
    if n < 1:
        raise ValueError(f"need n >= 1 requests, got {n}")
    if not specs:
        raise ValueError("tenant_requests needs at least one tenant")
    base = seed if seed_substream is None \
        else substream(seed, seed_substream)
    names = list(specs)
    mixes = [float(getattr(specs[name], "mix", 1.0)) for name in names]
    total_mix = sum(mixes)
    # Largest-remainder apportionment of n across tenants.
    raw = [n * m / total_mix for m in mixes]
    alloc = [int(r) for r in raw]
    for _ in range(n - sum(alloc)):
        rems = [(raw[i] - alloc[i], -i) for i in range(len(names))]
        i = -max(rems)[1]
        alloc[i] += 1
    out: List[Request] = []
    for idx, name in enumerate(names):
        if not alloc[idx]:
            continue
        spec = specs[name]
        reqs = synthetic_requests(
            alloc[idx], vocab_size=vocab_size, seed=base,
            prompt_len=prompt_len, max_new=max_new,
            temperature=temperature, top_k=top_k, eos_id=eos_id,
            stagger=stagger,
            burst=int(getattr(spec, "burst", 1)),
            deadline_steps=deadline_steps, deadline_s=deadline_s,
            shared_prefix=int(getattr(spec, "shared_prefix", 0)),
            seed_substream=idx, repetitive=repetitive)
        for req in reqs:
            req.tenant = name
        out.extend(reqs)
    # Stable merge on arrival step: within a step, spec order then
    # per-tenant FIFO — the order a FIFO engine would see, which is
    # exactly what the fair-vs-FIFO chaos comparisons key on.
    out.sort(key=lambda r: r.arrival_step or 0)
    return out


def parse_range(spec: str, name: str) -> Tuple[int, int]:
    """CLI range syntax: "8" (fixed) or "4:12" (inclusive range)."""
    parts = spec.split(":")
    try:
        if len(parts) == 1:
            lo = hi = int(parts[0])
        elif len(parts) == 2:
            lo, hi = int(parts[0]), int(parts[1])
        else:
            raise ValueError
    except ValueError:
        raise ValueError(f"--{name} wants N or MIN:MAX, got {spec!r}")
    if lo < 1 or lo > hi:
        raise ValueError(f"--{name}: bad range {lo}:{hi}")
    return lo, hi
