"""The continuous-batching scheduler loop.

One engine tick = admit + step + harvest:

1. **admit** — pop admissible requests from the queue into free slots
   (serve/slots.py resets that row's cache indices; the request's prompt
   becomes the slot's token feed).
2. **step** — ONE compiled decode program advances every live slot by
   one token.  Prefill and decode share the program exactly as in
   models/gpt.generate: a slot still inside its prompt feeds the next
   prompt token and discards the model's prediction; a slot past its
   prompt feeds its previously sampled token and keeps the new one.
   Because the cache indices are per-slot, requests admitted at
   different ticks coexist in one batch — continuous batching.
3. **harvest** — detect EOS / length completions, evict their slots,
   emit ``request_complete`` records (obs schema v3).

The per-tick host sync (fetching the sampled tokens) is the deliberate
cost of host-side scheduling, mirroring the telemetry layer's stance on
device fetches: the batch geometry stays static, so the compiled program
never changes — the TPU-native substrate for a serving engine.

Sampling is per-slot (temperature / top_k vectors through
models/gpt.sample_tokens), so greedy and sampled requests batch together.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_example_tpu.models.gpt import sample_tokens
from apex_example_tpu.obs.metrics import nearest_rank
from apex_example_tpu.serve.queue import Completion, Request, RequestQueue
from apex_example_tpu.serve.slots import SlotPool


def _now() -> float:
    return time.time()


def _pct_dict(vals_ms: List[float]) -> Dict[str, float]:
    s = sorted(vals_ms)
    return {"p50": round(nearest_rank(s, 50), 3),
            "p95": round(nearest_rank(s, 95), 3),
            "max": round(s[-1], 3) if s else 0.0}


@functools.lru_cache(maxsize=8)
def _slot_step(dec):
    """One compiled decode step for a slot-decode model clone (cached on
    the frozen module config, params as an argument — the same contract
    as models/gpt._decode_loop)."""

    @jax.jit
    def step(params, cache, tok, rng, temperature, top_k):
        logits, mut = dec.apply({"params": params, "cache": cache}, tok,
                                train=False, mutable=["cache"])
        nxt = sample_tokens(rng, logits[:, -1], temperature, top_k)
        return mut["cache"], nxt

    return step


def request_complete_record(comp: Completion,
                            run_id: Optional[str] = None) -> Dict[str, Any]:
    """The schema-v3 ``request_complete`` record for one completion."""
    rec: Dict[str, Any] = {
        "record": "request_complete",
        "time": _now(),
        "request_id": comp.request.uid,
        "prompt_tokens": len(comp.request.prompt),
        "output_tokens": len(comp.tokens),
        "ttft_ms": round(comp.ttft_s * 1e3, 3),
        "tpot_ms": round(comp.tpot_s * 1e3, 3),
        "finish_reason": comp.finish_reason,
        "slot": comp.slot,
        "queue_wait_ms": round(comp.queue_wait_s * 1e3, 3),
        "e2e_ms": round(comp.e2e_s * 1e3, 3),
        "admitted_step": comp.admitted_step,
        "finished_step": comp.finished_step,
        "temperature": float(comp.request.temperature),
        "top_k": int(comp.request.top_k),
    }
    if run_id:
        rec["run_id"] = run_id
    return rec


class ServeEngine:
    """Continuous-batching engine over a GPT-family model.

    ``model`` is the plain module, ``params`` its trained (or random)
    weights; the engine derives the slot-decode clone via its SlotPool.
    ``sink`` (an obs.JsonlSink), when given, receives one
    ``request_complete`` per finished request; the caller writes the
    run header and the final ``serve_summary`` (see serve.py).
    """

    def __init__(self, model, params, *, num_slots: int = 4,
                 max_len: int = 128, rng=None,
                 queue: Optional[RequestQueue] = None,
                 sink=None, run_id: Optional[str] = None):
        self.pool = SlotPool(model, num_slots, max_len)
        self.params = params
        self.queue = queue if queue is not None else RequestQueue()
        self.rng = rng if rng is not None else jax.random.PRNGKey(0)
        self.sink = sink
        self.run_id = run_id
        self.step_count = 0
        self.compute_steps = 0
        self.completions: List[Completion] = []
        self._step_fn = _slot_step(self.pool.dec)
        self._t0 = time.perf_counter()
        self._tokens_out = 0
        self._occupancy_sum = 0

    # ---------------------------------------------------------- intake

    def submit(self, request: Request) -> None:
        self.queue.submit(request)

    # ------------------------------------------------------------ tick

    def step(self) -> bool:
        """One engine tick.  Returns True when a decode step ran (some
        slot was live); False is an idle tick (virtual time still
        advances, so ``arrival_step`` gates keep maturing)."""
        pool = self.pool
        self.queue.mature(self.step_count)
        while pool.free_count:
            req = self.queue.pop(self.step_count)
            if req is None:
                break
            pool.admit(req, self.step_count)
        live = pool.live
        if not live:
            self.step_count += 1
            return False

        S = pool.num_slots
        tok = np.zeros((S, 1), np.int32)
        temps = np.zeros((S,), np.float32)
        ks = np.zeros((S,), np.int32)
        for i in live:
            slot = pool.slots[i]
            tok[i, 0] = slot.next_token()
            temps[i] = slot.request.temperature
            ks[i] = slot.request.top_k
        self.rng, key = jax.random.split(self.rng)
        pool.cache, nxt = self._step_fn(
            self.params, pool.cache, jnp.asarray(tok), key,
            jnp.asarray(temps), jnp.asarray(ks))
        nxt = np.asarray(nxt)          # the scheduler's host sync
        now = time.perf_counter()

        for i in live:
            slot = pool.slots[i]
            slot.cursor += 1
            if slot.prefilling:
                continue               # prompt token fed; output discarded
            out = int(nxt[i])
            if slot.n_generated == 0:
                slot.t_first_token = now
            slot.tokens.append(out)
            slot.n_generated += 1
            self._tokens_out += 1
            req = slot.request
            reason = None
            if req.eos_id is not None and out == req.eos_id:
                reason = "eos"
            elif slot.n_generated >= pool.max_new_for(req):
                reason = "length"
            if reason is not None:
                self._finish(i, reason, now)
        self.compute_steps += 1
        self._occupancy_sum += len(live)
        self.step_count += 1
        return True

    def _finish(self, idx: int, reason: str, now: float) -> None:
        slot = self.pool.slots[idx]
        comp = Completion(
            request=slot.request,
            tokens=slot.tokens[slot.n_prompt:],
            finish_reason=reason,
            slot=idx,
            admitted_step=slot.admitted_step,
            finished_step=self.step_count,
            t_admitted=slot.t_admitted,
            t_first_token=slot.t_first_token,
            t_finish=now)
        self.completions.append(comp)
        self.pool.evict(idx)
        if self.sink is not None:
            self.sink.write(request_complete_record(comp, self.run_id))

    # ------------------------------------------------------------ loop

    def run(self, max_steps: Optional[int] = None,
            idle_wait_s: float = 0.0) -> List[Completion]:
        """Drive ticks until the queue is drained and every slot is free
        (or ``max_steps`` ticks).  ``idle_wait_s`` throttles idle spins
        when a producer thread feeds the queue in wall-clock time."""
        while max_steps is None or self.step_count < max_steps:
            if self.queue.drained() and not self.pool.any_live():
                break
            ran = self.step()
            if not ran and idle_wait_s:
                time.sleep(idle_wait_s)
        return self.completions

    # --------------------------------------------------------- metrics

    def summary_record(self) -> Dict[str, Any]:
        """The schema-v3 ``serve_summary`` for everything completed so
        far (the caller writes it to the sink and closes)."""
        duration = time.perf_counter() - self._t0
        comps = self.completions
        rec: Dict[str, Any] = {
            "record": "serve_summary",
            "time": _now(),
            "requests": len(comps),
            "output_tokens": self._tokens_out,
            "tokens_per_sec": round(self._tokens_out / max(duration, 1e-9),
                                    1),
            "steps": self.step_count,
            "compute_steps": self.compute_steps,
            "slots": self.pool.num_slots,
            "max_len": self.pool.max_len,
            "duration_s": round(duration, 3),
        }
        if self.compute_steps:
            rec["occupancy"] = round(
                self._occupancy_sum / (self.compute_steps
                                       * self.pool.num_slots), 3)
        if comps:
            rec["ttft_ms"] = _pct_dict([c.ttft_s * 1e3 for c in comps])
            rec["tpot_ms"] = _pct_dict([c.tpot_s * 1e3 for c in comps])
            rec["queue_wait_ms"] = _pct_dict(
                [c.queue_wait_s * 1e3 for c in comps])
        if self.run_id:
            rec["run_id"] = self.run_id
        return rec
