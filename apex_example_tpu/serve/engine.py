"""The continuous-batching scheduler loop.

One engine tick = expire + admit + step + harvest:

1. **expire/shed** — stamp virtual arrivals, shed the backlog overflow
   (bounded admission, ``RequestQueue(max_pending=...)``), expire queued
   requests whose deadline passed without admission, and deadline-evict
   decoding slots whose request ran out of time mid-flight.
2. **admit** — pop admissible requests from the queue into free slots,
   gated by the BLOCK budget as well as the slot count: admission
   reserves a request's worst-case KV-block need (after prefix
   sharing, serve/slots.py), so out-of-blocks resolves here —
   deterministic head-of-line queueing (the popped head goes back to
   the queue front) — never as a stuck decoding slot.  A request the
   engine could NEVER serve (zero output budget: its prompt fills the
   cache; or a block need beyond the whole arena) terminates
   first-class with status "rejected" instead of occupying a slot to
   emit nothing.
3. **step** — ONE compiled decode program advances every live slot:
   a slot still inside its prompt feeds up to ``block_size`` prompt
   tokens (CHUNKED PREFILL — long prompts no longer take one tick per
   token) and discards every prediction except the one after its final
   prompt token; a slot past its prompt feeds its previously sampled
   token and keeps the new one.  Prefill chunks and decode steps ride
   the same program in the same batch (per-slot ``n_new`` lane
   counts), so requests admitted at different ticks coexist — and the
   K/V they cache live in block-paged arenas addressed through
   per-slot block tables (copy-on-write prefix sharing included)
   rather than dense per-slot pages.  The geometry is static; the
   program compiles exactly once.
4. **harvest** — detect EOS / length completions, evict their slots,
   emit ``request_complete`` records; per-slot host work is exception-
   contained, so a failure (or an injected ``slot_fail``) terminates
   only that slot's request (``request_failed`` record with the
   traceback digest) while the engine keeps ticking.  A NaN/degenerate-
   logits guard on the sampled-token path fails the affected slots the
   same way instead of feeding garbage back into the cache.

Every request terminates in a first-class ``Completion(status=...)``
(serve/queue.py: ok / timeout / shed / cancelled / failed / drained) —
overload, deadlines, faults and drains resolve requests explicitly
rather than silently dropping them.

Graceful drain (``drain()``): stop admission, hand queued requests back
with status "drained" (requeue-able on another replica), finish or
deadline-evict the in-flight slots, and emit a ``serve_drain`` record —
the serving counterpart of train.py's ``--preempt-grace`` path
(serve.py wires it to SIGTERM/SIGUSR1 and exits ``EX_TEMPFAIL``).

The per-tick host sync (fetching the sampled tokens) is the deliberate
cost of host-side scheduling, mirroring the telemetry layer's stance on
device fetches: the batch geometry stays static, so the compiled program
never changes — the TPU-native substrate for a serving engine.

Sharding (ISSUE 14): under a registered parallel_state mesh the engine
serves TP-sharded — weights placed per the training TP layers'
partition metadata and every paged-KV arena head-sharded over 'model'
(serve/slots.BlockPool.shard), while the block tables, free-list
allocator and admission logic above stay host-side and replicated.
The step lowers once per geometry with GSPMD shardings and greedy
output stays token-identical to the dense path.

Roles (ISSUE 14, serve/disagg.py): ``role="prefill"`` terminates each
request at its FIRST sampled token with status "handoff", shipping its
KV blocks through ``handoff_sink``; ``role="decode"`` admits such
handoffs (``admit_handoff``) and decodes with a [SLOTS, 1]-wide step —
its ticks stop paying for prefill lanes entirely.  ``role="both"``
(default) is the classic interleaved engine.

Sampling is per-slot (temperature / top_k vectors through
models/gpt.sample_tokens), so greedy and sampled requests batch together.
"""

from __future__ import annotations

import functools
import time
import traceback
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_example_tpu.models.gpt import sample_tokens
from apex_example_tpu.obs import costmodel as costmodel_lib
from apex_example_tpu.obs import trace as trace_lib
from apex_example_tpu.obs.metrics import Histogram, nearest_rank
from apex_example_tpu.obs.slo import SloTracker
from apex_example_tpu.resilience.faults import FaultInjected
from apex_example_tpu.serve.queue import (STATUSES, Completion, Request,
                                          RequestQueue)
from apex_example_tpu.serve.slots import BlockPool


def _wall() -> float:
    """Wall clock, for the ``time`` field of EMITTED RECORDS only.
    Every duration in this module is a difference of ``perf_counter``
    readings (the monotonic clock); the two domains meet nowhere except
    the ``clock_sync`` anchor a --trace run writes (obs/trace.py) —
    never in a subtraction."""
    return time.time()


def _pct_dict(vals_ms: List[float]) -> Dict[str, float]:
    s = sorted(vals_ms)
    return {"p50": round(nearest_rank(s, 50), 3),
            "p95": round(nearest_rank(s, 95), 3),
            "max": round(s[-1], 3) if s else 0.0}


@functools.lru_cache(maxsize=8)
def _slot_step(dec, dequant_weights: bool = False):
    """One compiled decode step for a PAGED slot-decode model clone
    (cached on the frozen module config — block geometry included —
    with params as an argument, the same contract as
    models/gpt._decode_loop).  ``tok`` is [SLOTS, C] with C =
    kv_block_size: a prefill chunk for slots inside their prompt, one
    token (lane 0) for decoding slots; ``n_new`` says how many lanes
    are real per slot, and sampling reads the logits AFTER each slot's
    last real token.  COW copies, the block-table K/V scatter and the
    gathered-attention live mask all run inside this one program
    (models/bert.py).  Besides the sampled tokens it returns a per-slot
    logits-finite mask: argmax/categorical over NaN logits yield an
    IN-RANGE index, so a token-range check alone can never see real NaN
    fallout — the finiteness of the logits themselves is the signal,
    and computing it here fuses it into the decode program.

    ``dequant_weights`` (ISSUE 13): params arrive as quant/weights.py's
    int8/fp8 {qvalue, scale} leaves and the dequant is the step's FIRST
    traced op — the low-bit bytes are the step's arguments (what HBM
    streams), and XLA fuses the scale multiply into each consuming
    matmul.  Part of the lru_cache key: arming quantization builds ONE
    new program; re-running either variant reuses its compile."""

    @jax.jit
    def step(params, cache, tok, block_table, fill, n_new, cow_src,
             cow_dst, rng, temperature, top_k):
        if dequant_weights:
            from apex_example_tpu.quant import weights as _qw
            params = _qw.dequantize_tree(params)
        paged = {"block_table": block_table, "fill": fill, "n_new": n_new,
                 "cow_src": cow_src, "cow_dst": cow_dst}
        logits, mut = dec.apply({"params": params, "cache": cache}, tok,
                                train=False, paged=paged,
                                mutable=["cache"])
        idx = jnp.clip(n_new - 1, 0, tok.shape[1] - 1)
        last = jnp.take_along_axis(logits, idx[:, None, None],
                                   axis=1)[:, 0]
        nxt = sample_tokens(rng, last, temperature, top_k)
        finite = jnp.all(jnp.isfinite(last), axis=-1)
        return mut["cache"], nxt, finite

    return step


@functools.lru_cache(maxsize=8)
def _slot_step_spec(dec, dequant_weights: bool = False):
    """The speculative variant of _slot_step (ISSUE 18): identical
    multi-lane dispatch — [SLOTS, C] tokens, per-slot n_new lane counts,
    COW + scatter + live mask all inside the one program — plus two
    extra outputs the accept/reject harvest needs host-side:

      * ``lane_greedy`` [SLOTS, C]: argmax over every lane's logits.
        Lane j's logits condition on lanes 0..j (causal live mask), so
        lane_greedy[s, j] is the model's greedy continuation after the
        j-th fed token — comparing it against the NEXT draft lane is
        the whole accept rule, and it reuses the same all-lane logits
        the chunked-prefill path already computes and discards.
      * ``lane_finite`` [SLOTS, C]: per-lane logits-finiteness, so NaN
        fallout in ANY verified lane poisons the slot, not just the
        last one.

    ``nxt`` still samples from the last REAL lane exactly like
    _slot_step, so sampled-temperature slots riding in the same batch
    behave token-identically to the plain path.  Cached per (module
    config, dequant flag): arming --speculate K builds exactly ONE new
    program for the [SLOTS, max(BS, K+1)] geometry."""

    @jax.jit
    def step(params, cache, tok, block_table, fill, n_new, cow_src,
             cow_dst, rng, temperature, top_k):
        if dequant_weights:
            from apex_example_tpu.quant import weights as _qw
            params = _qw.dequantize_tree(params)
        paged = {"block_table": block_table, "fill": fill, "n_new": n_new,
                 "cow_src": cow_src, "cow_dst": cow_dst}
        logits, mut = dec.apply({"params": params, "cache": cache}, tok,
                                train=False, paged=paged,
                                mutable=["cache"])
        idx = jnp.clip(n_new - 1, 0, tok.shape[1] - 1)
        last = jnp.take_along_axis(logits, idx[:, None, None],
                                   axis=1)[:, 0]
        nxt = sample_tokens(rng, last, temperature, top_k)
        finite = jnp.all(jnp.isfinite(last), axis=-1)
        lane_greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        lane_finite = jnp.all(jnp.isfinite(logits), axis=-1)
        return mut["cache"], nxt, finite, lane_greedy, lane_finite

    return step


def _current_mesh():
    """The registered parallel_state mesh, or None when serving runs
    unsharded (no mesh, or every axis trivial)."""
    from apex_example_tpu.transformer import parallel_state
    mesh = parallel_state.get_mesh()
    if mesh is None or all(s <= 1 for s in mesh.shape.values()):
        return None
    return mesh


def _shard_params(mesh, dec, params):
    """Place ``params`` per the TP layers' partition metadata (heads/
    vocab over 'model', everything else replicated) — the same
    device_put the TP generate() test does, extended to quantized
    trees: an int8/fp8 ``{qvalue, scale}`` leaf shards its qvalue like
    the original kernel (same shape, same spec) with the per-channel
    scale replicated (small, and a replicated multiplicand fuses
    cleanly into the sharded matmul)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_example_tpu.quant.weights import is_quantized_leaf
    from apex_example_tpu.transformer.tensor_parallel.layers import (
        param_partition_specs)
    abs_vars = jax.eval_shape(dec.init, jax.random.PRNGKey(0),
                              jnp.zeros((1, 4), jnp.int32))
    specs = param_partition_specs(abs_vars)["params"]
    spec_by_path = {
        jax.tree_util.keystr(path): s
        for path, s in jax.tree_util.tree_flatten_with_path(specs)[0]}
    flat, treedef = jax.tree_util.tree_flatten_with_path(
        params, is_leaf=is_quantized_leaf)
    out = []
    for path, leaf in flat:
        spec = spec_by_path.get(jax.tree_util.keystr(path), P())
        if is_quantized_leaf(leaf):
            out.append({
                "qvalue": jax.device_put(leaf["qvalue"],
                                         NamedSharding(mesh, spec)),
                "scale": jax.device_put(leaf["scale"],
                                        NamedSharding(mesh, P()))})
        else:
            out.append(jax.device_put(leaf, NamedSharding(mesh, spec)))
    return jax.tree_util.tree_unflatten(treedef, out)


def _weight_dtype_name(mode: str, params) -> str:
    """serve_summary's ``weight_dtype`` (schema v11): the storage dtype
    of the quant-eligible weight classes — via the AMP quant policy
    when quantization is armed (so fp8 reports its emulated spelling on
    a jax without native fp8), and the ACTUAL params dtype when it is
    not (a bf16 checkpoint must report bf16, not a hardcoded
    float32)."""
    if mode != "none":
        from apex_example_tpu.amp.policy import get_quant_policy
        return get_quant_policy(mode).weight_dtype_name
    for leaf in jax.tree_util.tree_leaves(params):
        if hasattr(leaf, "dtype"):
            return str(leaf.dtype)
    return "none"


class SlotFailure(RuntimeError):
    """Raised inside one slot's harvest when its sampled token is
    degenerate (out-of-vocab / NaN-logits fallout) — contained to that
    slot like any other per-slot exception."""


def request_complete_record(comp: Completion,
                            run_id: Optional[str] = None, *,
                            with_tenant: bool = False) -> Dict[str, Any]:
    """The schema-v3 ``request_complete`` record for one ok completion.
    ``with_tenant`` (v17) stamps the scheduling lane — only set when
    tenancy is armed, so legacy streams stay byte-identical."""
    rec: Dict[str, Any] = {
        "record": "request_complete",
        "time": _wall(),
        "request_id": comp.request.uid,
        "prompt_tokens": len(comp.request.prompt),
        "output_tokens": len(comp.tokens),
        "ttft_ms": round((comp.ttft_s or 0.0) * 1e3, 3),
        "tpot_ms": round(comp.tpot_s * 1e3, 3),
        "finish_reason": comp.finish_reason,
        "slot": comp.slot,
        "queue_wait_ms": round((comp.queue_wait_s or 0.0) * 1e3, 3),
        "e2e_ms": round(comp.e2e_s * 1e3, 3),
        "admitted_step": comp.admitted_step,
        "finished_step": comp.finished_step,
        "temperature": float(comp.request.temperature),
        "top_k": int(comp.request.top_k),
    }
    if with_tenant:
        rec["tenant"] = getattr(comp.request, "tenant", "default")
    if run_id:
        rec["run_id"] = run_id
    return rec


def request_failed_record(comp: Completion,
                          run_id: Optional[str] = None, *,
                          with_tenant: bool = False) -> Dict[str, Any]:
    """The schema-v5 ``request_failed`` record for a timeout / cancelled
    / failed completion (drained requests ride the ``serve_drain``
    record instead — they are requeued, not failed)."""
    rec: Dict[str, Any] = {
        "record": "request_failed",
        "time": _wall(),
        "request_id": comp.request.uid,
        "status": comp.status,
        "prompt_tokens": len(comp.request.prompt),
        "output_tokens": len(comp.tokens),
        "failed_step": comp.finished_step,
    }
    if comp.slot >= 0:
        rec["slot"] = comp.slot
        rec["admitted_step"] = comp.admitted_step
    if comp.queue_wait_s is not None:
        rec["queue_wait_ms"] = round(comp.queue_wait_s * 1e3, 3)
    rec["e2e_ms"] = round(comp.e2e_s * 1e3, 3)
    if comp.error:
        rec["error"] = comp.error
    if with_tenant:
        rec["tenant"] = getattr(comp.request, "tenant", "default")
    if run_id:
        rec["run_id"] = run_id
    return rec


class ServeEngine:
    """Continuous-batching engine over a GPT-family model.

    ``model`` is the plain module, ``params`` its trained (or random)
    weights; the engine derives the paged slot-decode clone via its
    BlockPool (``block_size`` sets both the arena granularity and the
    chunked-prefill width; ``num_blocks`` defaults to dense capacity).
    ``sink`` (an obs.JsonlSink), when given, receives one
    ``request_complete`` / ``request_failed`` / ``shed`` record per
    terminated request; the caller writes the run header and the final
    ``serve_summary`` (see serve.py).  ``fault`` is an optional
    resilience ``FaultPlan`` whose step is a 1-based engine tick
    (``--inject-fault kind@tick``).
    """

    def __init__(self, model, params, *, num_slots: int = 4,
                 max_len: int = 128, block_size: int = 8,
                 num_blocks: Optional[int] = None, rng=None,
                 queue: Optional[RequestQueue] = None,
                 sink=None, run_id: Optional[str] = None,
                 fault=None, registry=None, kv_quant: bool = False,
                 weight_quant: str = "none", role: str = "both",
                 handoff_sink=None, slo=None,
                 slo_window_s: Optional[float] = None,
                 slo_window_ticks: int = 0, tick_profiler=None,
                 speculate: int = 0, proposer=None,
                 tenants=None, tag_tenants: bool = False,
                 advertise_prefixes: int = 0):
        if weight_quant not in ("none", "int8", "fp8"):
            raise ValueError(f"weight_quant must be none|int8|fp8, got "
                             f"{weight_quant!r}")
        if role not in ("both", "prefill", "decode"):
            raise ValueError(f"role must be both|prefill|decode, got "
                             f"{role!r}")
        if role == "prefill" and handoff_sink is None:
            raise ValueError("a prefill-role engine needs a "
                             "handoff_sink to ship finished prefills to "
                             "(serve/disagg.py transports)")
        if speculate < 0:
            raise ValueError(f"speculate must be >= 0, got {speculate}")
        if speculate and role != "both":
            raise ValueError("--speculate needs the interleaved engine "
                             "(role 'both'); disaggregated roles keep "
                             "their own step geometries")
        if speculate and speculate + 1 > max_len:
            raise ValueError(f"speculate {speculate} exceeds max_len "
                             f"{max_len} lanes")
        self.pool = BlockPool(model, num_slots, max_len,
                              block_size=block_size,
                              num_blocks=num_blocks, kv_quant=kv_quant,
                              spec_slack=speculate)
        # weight_quant names the mode ``params`` ALREADY carries (the
        # caller quantized at restore time — serve.py); the engine's
        # job is to dequantize inside the compiled step.
        self.weight_quant = weight_quant
        self.vocab_size = int(model.vocab_size)
        # Disaggregation (ISSUE 14): a "prefill" engine chunk-prefills
        # prompts, samples each request's FIRST token, then ships its
        # KV blocks through ``handoff_sink`` (status "handoff"); a
        # "decode" engine admits those payloads via admit_handoff() and
        # decodes ONE token per live slot per tick — its compiled step
        # is [SLOTS, 1]-wide, so decode ticks stop paying for the
        # [SLOTS, block_size] prefill geometry entirely.  "both" is the
        # classic interleaved engine.
        self.role = role
        self.handoff_sink = handoff_sink
        self.chunk = 1 if role == "decode" else self.pool.block_size
        # Speculation (ISSUE 18): K draft tokens per greedy slot per
        # tick, verified in ONE dispatch.  The step stays [SLOTS, C]
        # with C = max(block_size, K+1): prefill chunks and draft lanes
        # share the same static geometry, so arming --speculate K adds
        # exactly one compiled program (serve_spec_step) regardless of
        # acceptance behavior.  speculate == 0 leaves every line of the
        # plain path untouched.
        self.speculate = int(speculate)
        self.proposer = proposer
        if self.speculate and self.proposer is None:
            from apex_example_tpu.spec import NgramProposer
            self.proposer = NgramProposer()
        if self.speculate:
            self.chunk = max(self.chunk, self.speculate + 1)
        self.tokens_drafted = 0
        self.tokens_accepted = 0
        self.tokens_sampled = 0
        self.handoffs_in = 0
        self.handoff_requeued = 0
        self._handoff_bytes = 0
        self._handoff_ms: List[float] = []
        # Idempotent admission (ISSUE 15): uids this engine already
        # admitted — a redelivered claim (worker died between admit and
        # ack; duplicate delivery after lease skew) is detected here
        # and acked WITHOUT a second scatter.  A restarted fleet
        # replica seeds it from its outbox so a handoff completed just
        # before the crash is never served twice (serve.py).
        self.handoff_seen: set = set()
        self.handoff_redelivered: set = set()   # uids admitted from a
        #                                         reclaimed/adopted lease
        self.handoff_duplicates = 0
        # Live migration (ISSUE 20): MID-FLIGHT requests shipped whole —
        # KV blocks, generated tokens and sampler state — to a peer that
        # resumes them (extract_live / admit_migrated).  Same transport,
        # same idempotence set (handoff_seen keys on uid, and a uid is
        # admitted here at most once regardless of payload kind), its
        # own counters so the v18 summary can tell a drain-without-
        # eviction from a prefill->decode pipeline.
        self.migrations_in = 0
        self.migration_requeued = 0
        self.migration_duplicates = 0
        self.migration_redelivered: set = set()
        self._migration_bytes = 0
        self._migration_ms: List[float] = []
        # Mesh awareness: under a registered parallel_state mesh the
        # weights and per-layer KV arenas shard over heads on the
        # 'model' axis (the bert/gpt constraint points from the TP
        # training path do the in-trace work); block tables, free-list
        # and admission stay host-side and replicated.  The compiled
        # step lowers ONCE per geometry with GSPMD shardings; pallas
        # kernels are opaque to the partitioner, so sharded calls pin
        # the XLA reference ops exactly like generate() under TP.
        self.mesh = None
        self.dp = self.tp = 1
        mesh = _current_mesh()
        if mesh is not None:
            from apex_example_tpu.parallel.mesh import (
                DATA_AXIS, require_model_axis_match)
            self.tp = require_model_axis_match(
                mesh, bool(model.tensor_parallel))
            self.dp = mesh.shape.get(DATA_AXIS, 1)
            self.mesh = mesh
            params = _shard_params(mesh, self.pool.dec, params)
            self.pool.shard(mesh)
        self.params = params
        self.queue = queue if queue is not None else RequestQueue()
        self.rng = rng if rng is not None else jax.random.PRNGKey(0)
        self.sink = sink
        self.run_id = run_id
        self.fault = fault
        self.registry = registry
        self.step_count = 0
        self.compute_steps = 0
        self.completions: List[Completion] = []
        self.counts: Dict[str, int] = {s: 0 for s in STATUSES}
        self.draining = False
        # --cost-model (obs/costmodel.py): when a default instance is
        # installed, the decode step compiles through the AOT path and
        # that ONE compilation lands as compile_event + cost_model
        # records — the batch geometry is static, so a second
        # compile_event for this name is a recompile regression.  The
        # prefill role instruments under its own name: its program is
        # [SLOTS, block_size]-wide while the decode role's is
        # [SLOTS, 1]-wide — one program per role, each compiling once.
        if self.speculate:
            self._step_fn = costmodel_lib.instrument(
                "serve_spec_step",
                _slot_step_spec(self.pool.dec,
                                dequant_weights=weight_quant != "none"))
        else:
            self._step_fn = costmodel_lib.instrument(
                "serve_prefill_step" if role == "prefill"
                else "serve_decode_step",
                _slot_step(self.pool.dec,
                           dequant_weights=weight_quant != "none"))
        self._t0 = time.perf_counter()
        self._tokens_out = 0
        self._occupancy_sum = 0
        # Per-compute-tick gauges (schema v6/v7 serve_summary): live
        # slots, logical KV bytes, physically-held arena blocks and
        # admission-committed bytes — block-accurate occupancy (the
        # dense-page layout these replace measured ~92% kv_waste_pct).
        self._occ_hist = Histogram("serve.slots_live")
        self._kv_hist = Histogram("serve.kv_bytes_live")
        self._blk_hist = Histogram("serve.blocks_live")
        self._committed_hist = Histogram("serve.kv_bytes_committed")
        # --trace (obs/trace.py): the process-default tracer, when one
        # is armed, receives the per-tick admit/dispatch/harvest spans
        # and a per-request lifecycle span tree.  Everything below is
        # host-side bookkeeping of timestamps the engine already takes:
        # tracing changes NO device work and the compiled decode step
        # is untouched.  _rtrace buffers each admitted request's
        # prefill-chunk windows so its whole tree can be emitted in
        # timestamp order at terminal time (a request stranded
        # mid-flight at a --steps cap simply never emits, rather than
        # leaving an unbalanced span behind).
        self._tracer = trace_lib.get_default()
        self._rtrace: Dict[str, List] = {}
        # --slo (obs/slo.py, ISSUE 16): the streaming SLO fold — pure
        # host-side state the terminal funnel and the per-tick gauge
        # block feed; windows close on wall time (slo_window_s) or
        # engine ticks (slo_window_ticks, the deterministic mode) and
        # emit slo_window/slo_breach records through the same sink.
        # The compiled step is untouched: arming --slo adds ZERO
        # compiled programs (the cost-model test asserts it).
        self.slo: Optional[SloTracker] = None
        if slo:
            self.slo = SloTracker(
                slo,
                window_s=slo_window_s if slo_window_s else 1.0,
                window_ticks=slo_window_ticks or 0,
                emit=sink.write if sink is not None else None,
                run_id=run_id)
        # --tick-profile (obs/tickprof.py, ISSUE 17): per-tick phase
        # decomposition.  Armed, the step inserts ONE extra
        # block_until_ready at the enqueue/device boundary — a
        # value-preserving host sync on outputs the tick was about to
        # block on anyway (np.asarray), so greedy outputs stay
        # token-identical and NO new program compiles.  Unarmed, the
        # tick path is unchanged.  Idle-spin accounting (idle_ticks /
        # idle_wait_ms) is always on: it is free.
        self.tickprof = tick_profiler
        self.idle_ticks = 0
        self.idle_wait_ms = 0.0
        self._spool_ms = 0.0
        # --tenants (sched/, ISSUE 19): deficit-weighted round-robin
        # admission over per-tenant lanes.  The intake RequestQueue
        # stays exactly as-is (arrival gating, shed_overflow, queued
        # cancellation); matured pops drain into the scheduler's lanes
        # and the admit loop draws from DWRR order instead of FIFO.
        # Unarmed (tenants=None) the admit path is UNTOUCHED — streams
        # stay byte-identical to pre-v17 output.  Zero device work
        # either way: scheduling is pure host bookkeeping.
        self.sched = None
        if tenants is not None:
            from apex_example_tpu.sched import FairScheduler
            self.sched = FairScheduler(tenants)
        # Tenant stamps on terminal records normally ride with the
        # fair scheduler, but a FIFO control arm (tenancy measured,
        # fairness dropped) still needs them — its stream feeds the
        # same ci_gate --tenant-stream conservation ledger.
        self.tag_tenants = bool(tag_tenants) or self.sched is not None
        # --advertise-prefixes N (ISSUE 19): publish the N hottest
        # prefix chain-key digests + raw reuse counters in replica
        # heartbeats so the fleet router can route on KV CONTENT
        # (policy prefix_affinity).  Opt-in to keep unarmed heartbeats
        # byte-identical.
        if advertise_prefixes < 0:
            raise ValueError(f"advertise_prefixes must be >= 0, got "
                             f"{advertise_prefixes}")
        self.advertise_prefixes = int(advertise_prefixes)

    # ---------------------------------------------------------- intake

    def submit(self, request: Request) -> None:
        self.queue.submit(request)

    def cancel(self, uid: str) -> bool:
        """Cancel a request by uid: a queued one terminates immediately
        (status "cancelled", never admitted); a decoding one is evicted
        mid-flight with its partial tokens.  False if the uid is unknown
        or already terminal.  Call from the engine thread (queued-side
        cancellation alone is thread-safe via the queue's lock)."""
        req = self.queue.cancel(uid)
        if req is None and self.sched is not None:
            req = self.sched.cancel(uid)
        if req is not None:
            self._terminal_unadmitted(req, "cancelled")
            return True
        for i in self.pool.live:
            slot = self.pool.slots[i]
            if slot.request.uid == uid:
                self._terminal_slot(i, "cancelled", time.perf_counter())
                return True
        return False

    # ------------------------------------------------------------ tick

    def step(self) -> bool:
        """One engine tick.  Returns True when a decode step ran (some
        slot was live); False is an idle tick (virtual time still
        advances, so ``arrival_step`` gates keep maturing)."""
        pool = self.pool
        step = self.step_count
        tick1 = step + 1            # 1-based, for --inject-fault kind@tick
        now = time.perf_counter()
        t_tick_start = now          # ``now`` is re-taken post-dispatch
        if not self.draining:
            self.queue.mature(step)
            # Expire BEFORE evaluating the bound: requests already dead
            # in the queue must not count against max_pending and get a
            # healthy arrival shed over capacity that frees this tick.
            for req in self.queue.expire(step, now):
                self._terminal_unadmitted(req, "timeout")
            shed = self.queue.shed_overflow(step)
            if shed:
                # One arrived-backlog read for the whole batch of shed
                # records, not one O(backlog) scan per victim.
                pending = self.queue.arrived_pending(step)
                for req in shed:
                    self._terminal_unadmitted(req, "shed",
                                              pending=pending)
        # Mid-flight deadline eviction (drain included: "finish or
        # deadline-evict" is the drain contract) — checked at the tick
        # boundary, before the slot consumes another decode step.
        for i in list(pool.live):
            if pool.slots[i].request.expired(step, now):
                self._terminal_slot(i, "timeout", now)
        if not self.draining:
            sched = self.sched
            if sched is not None:
                # Tenancy armed (ISSUE 19): drain every matured intake
                # pop into the per-tenant lanes, sweep lane deadlines
                # the same tick the intake queue sweeps its own, and —
                # once intake is closed — finalize budget-parked heads
                # that can provably never admit (budgets never
                # replenish) so the run loop terminates.
                while True:
                    q_req = self.queue.pop(step)
                    if q_req is None:
                        break
                    sched.enqueue(q_req)
                for req in sched.expire(step, now):
                    self._terminal_unadmitted(req, "timeout")
                if self.queue.drained():
                    for req in sched.reject_overbudget_heads():
                        self._terminal_unadmitted(req, "rejected")
            while pool.free_count:
                req = sched.next() if sched is not None \
                    else self.queue.pop(step)
                if req is None:
                    break
                if not pool.fits(req):
                    # The satellite bugfix (ISSUE 8): a request whose
                    # prompt fills the cache (max_new_for == 0) — or
                    # whose worst-case block need exceeds the whole
                    # arena — used to occupy a slot and terminate with
                    # ZERO generated tokens.  It can never be served
                    # here; reject it first-class at admission.
                    if sched is not None:
                        sched.refund(req)   # unservable ≠ tenant spend
                    self._terminal_unadmitted(req, "rejected")
                    continue
                if not pool.can_admit(req):
                    # Out of KV blocks: deterministic head-of-line
                    # queueing — the head waits at the queue front
                    # until evictions free its worst-case budget (FIFO
                    # preserved; bounded, since every live slot
                    # finishes within max_len ticks).  The scheduler's
                    # push_front also refunds the budget debit.
                    if sched is not None:
                        sched.push_front(req)
                    else:
                        self.queue.push_front(req)
                    break
                pool.admit(req, step)
                if self._tracer is not None:
                    self._rtrace[req.uid] = []   # prefill-chunk buffer
        live = pool.live
        if not live:
            self.idle_ticks += 1
            self.step_count += 1
            if self.fault is not None:
                # Engine-level kinds are defined on TICKS, not decode
                # steps — an idle tick must still fire crash/sigterm/
                # hang, or a drill scheduled between arrival waves would
                # be silently skipped (equality never matches again).
                self.fault.maybe_fire(tick1)
            return False

        tracer = self._tracer
        prof = self.tickprof
        tick_sid = None
        t_admit_end = now
        if tracer is not None or prof is not None:
            # Admit-phase boundary: taken once, shared by the tracer
            # span and the profiler's phase fold.
            t_admit_end = time.perf_counter()
        if tracer is not None:
            # The tick span opens retroactively at the tick boundary
            # (``now``, taken before expire/admit ran) so the admit
            # phase is inside it; idle ticks emit nothing — a
            # wall-clock producer's idle spin must not flood the
            # stream.
            tick_sid = tracer.begin("tick", tid="engine", ts=now,
                                    cat="tick",
                                    args={"tick": step,
                                          "live": len(live)})
            tracer.complete("admit", now, t_admit_end - now,
                            tid="engine", cat="tick",
                            parent_id=tick_sid)
        # Chunk width: block_size for interleaved/prefill engines, ONE
        # for a decode-role engine — its slots only ever feed a single
        # token per tick (handoffs arrive pre-filled), so its compiled
        # step drops the prefill lanes and each decode tick pays
        # 1/block_size of the interleaved program's token FLOPs: the
        # decode-tick stall the disaggregation removes.
        S, C = pool.num_slots, self.chunk
        tok = np.zeros((S, C), np.int32)
        fill = np.zeros((S,), np.int32)
        n_new = np.zeros((S,), np.int32)
        cow_src = np.full((S,), -1, np.int32)
        cow_dst = np.full((S,), -1, np.int32)
        temps = np.zeros((S,), np.float32)
        ks = np.zeros((S,), np.int32)
        drafts: Dict[int, List[int]] = {}
        for i in live:
            slot = pool.slots[i]
            # Chunked prefill: up to one block of prompt tokens per
            # tick; decode feeds the single previously-sampled token.
            n = min(C, slot.n_prompt - slot.cursor) if slot.prefilling \
                else 1
            if self.speculate and not slot.prefilling \
                    and slot.request.temperature == 0:
                # Speculative decode lanes: the last sampled token plus
                # up to K host-drafted candidates, verified in the same
                # dispatch.  Sampled-temperature slots keep the plain
                # single-lane path — speculation is greedy-only.
                draft = self._draft_for(slot)
                drafts[i] = draft
                n = 1 + len(draft)
                tok[i, :n] = [slot.tokens[slot.cursor]] + draft
            else:
                tok[i, :n] = slot.tokens[slot.cursor:slot.cursor + n]
            fill[i] = slot.cursor
            n_new[i] = n
            # Map/COW the blocks this slot writes this tick (draws from
            # the budget reserved at admission, so it cannot OOM).
            cow_src[i], cow_dst[i] = pool.stage_writes(i, n)
            temps[i] = slot.request.temperature
            ks[i] = slot.request.top_k
        self.rng, key = jax.random.split(self.rng)
        if self.mesh is not None:
            # Pallas custom calls are opaque to the SPMD partitioner;
            # pin the XLA reference ops for the sharded trace exactly
            # like generate() under TP (the compiled program is cached,
            # so this costs nothing after the first call).
            from apex_example_tpu.ops import _config as ops_config
            with ops_config.force_xla():
                outs = self._step_fn(
                    self.params, pool.cache, jnp.asarray(tok),
                    jnp.asarray(pool.table), jnp.asarray(fill),
                    jnp.asarray(n_new), jnp.asarray(cow_src),
                    jnp.asarray(cow_dst), key,
                    jnp.asarray(temps), jnp.asarray(ks))
        else:
            outs = self._step_fn(
                self.params, pool.cache, jnp.asarray(tok),
                jnp.asarray(pool.table), jnp.asarray(fill),
                jnp.asarray(n_new), jnp.asarray(cow_src),
                jnp.asarray(cow_dst), key,
                jnp.asarray(temps), jnp.asarray(ks))
        lane_greedy = lane_finite = None
        if self.speculate:
            pool.cache, nxt, finite, lane_greedy, lane_finite = outs
        else:
            pool.cache, nxt, finite = outs
        t_enqueue_end = t_device_end = 0.0
        if prof is not None:
            # The dispatch/device boundary ISSUE 17 exists to draw:
            # the compiled call has returned (enqueue cost paid) but
            # its outputs may still be computing.  Blocking HERE — on
            # values the np.asarray sync below was about to block on
            # anyway — splits enqueue from device execution without
            # changing any value or compiling anything new.  (On CPU
            # jax dispatch is synchronous, so device_wait reads ~0 and
            # the device time hides in dispatch_enqueue; see README.)
            t_enqueue_end = time.perf_counter()
            jax.block_until_ready(outs)
            t_device_end = time.perf_counter()
            self._spool_ms = 0.0
        nxt = np.asarray(nxt)          # the scheduler's host sync
        finite = np.asarray(finite)
        if self.speculate:
            lane_greedy = np.asarray(lane_greedy)
            lane_finite = np.asarray(lane_finite)
        now = time.perf_counter()
        t_dispatch_end = now
        if tracer is not None:
            # Dispatch = host marshal + the compiled step + the host
            # sync above: what one tick paid for device work.
            tracer.complete("dispatch", t_admit_end, now - t_admit_end,
                            tid="engine", cat="tick",
                            parent_id=tick_sid,
                            args={"lanes": int(n_new.sum())})

        fault = self.fault
        fail_slot = -1
        if fault is not None:
            if fault.kind == "nan" and fault.due(tick1):
                # Degenerate-sampling drill: what NaN logits do to the
                # sampled-token path, deterministically.  The guard below
                # fails every affected slot instead of feeding the
                # garbage token back into the cache.  Only consumed when
                # some slot actually KEEPS this tick's token — a slot
                # still short of its prompt end after this tick's chunk
                # discards the output, and the drill would be spent with
                # zero effect, so it defers to the first tick that can
                # express it (FaultPlan.due is >=, and the serve path
                # has no resume to double-fire).
                slots = pool.slots
                if any(slots[i].cursor + int(n_new[i])
                       >= slots[i].n_prompt for i in live):
                    fault.take()
                    nxt = np.full_like(nxt, -1)
                    if lane_greedy is not None:
                        # Speculative slots harvest from the verify
                        # lanes, not nxt — poison those too so the
                        # drill expresses under --speculate.
                        lane_greedy = np.full_like(lane_greedy, -1)
            elif fault.kind == "slot_fail" and fault.due(tick1):
                fault.take()
                fail_slot = live[0]

        for i in live:
            slot = pool.slots[i]
            reason = None
            was_prefilling = slot.prefilling
            try:
                if i == fail_slot:
                    raise FaultInjected(
                        f"injected slot_fail at tick {tick1} (slot {i})")
                if i in drafts:
                    # Speculative accept/reject harvest: appends the
                    # accepted draft prefix + the bonus token from the
                    # first mismatching lane, and commits only lanes
                    # with canonical KV — rollback for rejected lanes
                    # is the cursor simply not advancing past them.
                    reason = self._harvest_spec(
                        i, drafts[i], lane_greedy, lane_finite,
                        int(n_new[i]), now)
                else:
                    pool.commit_writes(i, int(n_new[i]))
                    if tracer is not None and was_prefilling:
                        # Buffer the chunk window (the tick's dispatch
                        # span) on the request; its tree is emitted
                        # whole, in timestamp order, at terminal time.
                        self._rtrace.setdefault(
                            slot.request.uid, []).append(
                            (t_admit_end, t_dispatch_end, int(n_new[i]),
                             int(cow_dst[i]) >= 0))
                    if slot.prefilling:
                        continue       # prompt chunk fed; output discarded
                    out = int(nxt[i])
                    if not bool(finite[i]):
                        raise SlotFailure(
                            f"non-finite logits in slot {i} — NaN/Inf "
                            "reached the sampled-token path (poisoned "
                            "params or cache row)")
                    if not 0 <= out < self.vocab_size:
                        raise SlotFailure(
                            f"degenerate sampled token {out} (vocab "
                            f"{self.vocab_size}) — poisoned sampling "
                            "path")
                    if slot.n_generated == 0:
                        slot.t_first_token = now
                    slot.tokens.append(out)
                    slot.n_generated += 1
                    self._tokens_out += 1
                    self.tokens_sampled += 1
                    req = slot.request
                    if req.eos_id is not None and out == req.eos_id:
                        reason = "eos"
                    elif slot.n_generated >= pool.max_new_for(req):
                        reason = "length"
            except Exception as e:   # noqa: BLE001 — slot-level isolation
                # One request's failure must not take down the batch: the
                # other slots' caches and host state are untouched, so
                # their token streams continue bit-exact.
                self._terminal_slot(i, "failed", now, error=e)
                continue
            # Terminal transitions run OUTSIDE the isolation try: a sink
            # IO failure inside _finish is an ENGINE-level fault (it
            # would hit every record), and catching it above would both
            # mislabel it a slot failure and re-terminate an
            # already-evicted slot.
            if reason is not None:
                self._finish(i, reason, now)
            elif self.role == "prefill" and slot.n_generated == 1:
                # Prefill role: the prompt is fully cached and the
                # FIRST token sampled — ship the KV blocks to a decode
                # worker instead of occupying a prefill slot with
                # 1-token decode ticks.  (A request whose first token
                # already finished it — eos, or a 1-token budget —
                # completed above and never transits.)
                self._handoff_slot(i, now)
        self.compute_steps += 1
        self._occupancy_sum += len(live)
        t_harvest_end = time.perf_counter() if prof is not None else 0.0
        # Gauge the tick AFTER harvest: what is RESIDENT at the tick
        # boundary (a finished slot's blocks were just unref'd — the
        # reclamation the dense layout could never express).
        live_slots = len(self.pool.live)
        kv_live = self.pool.kv_bytes_live()
        blocks_live = self.pool.blocks_live()
        per_block = self.pool.block_size * self.pool.kv_bytes_per_token()
        self._occ_hist.observe(live_slots)
        self._kv_hist.observe(kv_live)
        self._blk_hist.observe(blocks_live)
        self._committed_hist.observe(
            self.pool.blocks_committed() * per_block)
        if self.registry is not None:
            self.registry.gauge("serve.slots_live").set(live_slots)
            self.registry.gauge("serve.kv_bytes_live").set(kv_live)
            self.registry.gauge("serve.blocks_live").set(blocks_live)
        if self.slo is not None:
            self.slo.observe_tick(live_slots=live_slots,
                                  num_slots=self.pool.num_slots,
                                  blocks_live=blocks_live,
                                  kv_bytes_live=kv_live)
        if tracer is not None:
            t_end = time.perf_counter()
            tracer.complete("harvest", t_dispatch_end,
                            t_end - t_dispatch_end, tid="engine",
                            cat="tick", parent_id=tick_sid,
                            args={"live": live_slots,
                                  "blocks": blocks_live})
            tracer.end("tick", tid="engine", ts=t_end)
        self.step_count += 1
        if prof is not None:
            # Contiguous boundaries telescope: the six phases sum to
            # the measured wall EXACTLY (modulo float rounding), which
            # is what perf_ledger's 1% consistency gate verifies.  The
            # profiler's own record emit happens after t_tick_end and
            # never pollutes the measurement.
            t_tick_end = time.perf_counter()
            spool = self._spool_ms
            prof.observe_tick(
                t_tick_start,
                (t_tick_end - t_tick_start) * 1e3,
                admit=(t_admit_end - t_tick_start) * 1e3,
                dispatch_enqueue=(t_enqueue_end - t_admit_end) * 1e3,
                device_wait=(t_device_end - t_enqueue_end) * 1e3,
                harvest=(t_harvest_end - t_device_end) * 1e3 - spool,
                spool_io=spool,
                telemetry=(t_tick_end - t_harvest_end) * 1e3)
        if fault is not None:
            # crash/sigterm/hang fire AFTER the tick's harvest (matching
            # the training loops: forensics hold the last good tick).
            fault.maybe_fire(tick1)
        return True

    # ------------------------------------------------------ speculation

    def _draft_for(self, slot) -> List[int]:
        """Ask the proposer for this tick's draft, clamped so staged KV
        writes can never outrun the slot's logical budget: at most K
        lanes, at most chunk-1 (the program's spare lane count), and at
        most remaining-1 — the +1 bonus token of a fully-accepted draft
        must still fit under max_new_for.  A proposer returning junk
        (out-of-vocab ids) is truncated at the first bad token; draft
        QUALITY can only cost throughput, never correctness."""
        req = slot.request
        remaining = self.pool.max_new_for(req) - slot.n_generated
        k = min(self.speculate, remaining - 1, self.chunk - 1)
        if k <= 0:
            return []
        draft = self.proposer.propose(req.uid, req.prompt,
                                      slot.tokens[slot.n_prompt:], k)
        out: List[int] = []
        for t in list(draft)[:k]:
            t = int(t)
            if not 0 <= t < self.vocab_size:
                break
            out.append(t)
        return out

    def _harvest_spec(self, i: int, draft: List[int], lane_greedy,
                      lane_finite, n: int, now: float) -> Optional[str]:
        """Accept/reject harvest for one speculative slot.  The fed
        lanes were [last_sampled, d0..d_{k-1}]; lane j's logits
        condition on everything up to and including lane j, so
        lane_greedy[j] is the model's greedy choice for the position
        draft[j] claims.  Accept the longest matching prefix d0..d_{m-1}
        plus the bonus token lane_greedy[m] (the model's own pick at the
        first mismatch — or after a fully-accepted draft), walking
        eos/length exactly as m+1 one-token ticks would have.  Commit
        1 + kept-draft lanes: the bonus token has no KV yet (it is next
        tick's lane 0), and rejected lanes' stale rows sit beyond the
        cursor where the live mask hides them until overwritten."""
        pool = self.pool
        slot = pool.slots[i]
        req = slot.request
        lanes = lane_greedy[i]
        if not bool(lane_finite[i, :n].all()):
            raise SlotFailure(
                f"non-finite logits in slot {i} — NaN/Inf reached a "
                "speculative verify lane (poisoned params or cache "
                "row)")
        m = 0
        while m < len(draft) and int(lanes[m]) == draft[m]:
            m += 1
        bonus = int(lanes[m])
        if not 0 <= bonus < self.vocab_size:
            raise SlotFailure(
                f"degenerate greedy token {bonus} (vocab "
                f"{self.vocab_size}) — poisoned sampling path")
        self.tokens_drafted += len(draft)
        if slot.n_generated == 0:
            slot.t_first_token = now
        reason = None
        n_keep = 0
        budget = pool.max_new_for(req)
        for pos, t in enumerate(draft[:m] + [bonus]):
            slot.tokens.append(t)
            slot.n_generated += 1
            self._tokens_out += 1
            n_keep += 1
            if pos < m:
                self.tokens_accepted += 1
            else:
                self.tokens_sampled += 1
            if req.eos_id is not None and t == req.eos_id:
                reason = "eos"
                break
            if slot.n_generated >= budget:
                reason = "length"
                break
        pool.commit_writes(i, 1 + min(n_keep, m))
        return reason

    # ------------------------------------------------------- terminals

    def _finish(self, idx: int, reason: str, now: float) -> None:
        self._evict_terminal(idx, reason, "ok", now)

    def _terminal_slot(self, idx: int, status: str, now: float,
                       error: Optional[BaseException] = None) -> None:
        """Evict a live slot with a non-ok status (timeout / cancelled /
        failed): partial tokens kept, ``request_failed`` emitted."""
        self._evict_terminal(idx, status, status, now, error=error)

    def _evict_terminal(self, idx: int, finish_reason: str, status: str,
                        now: float,
                        error: Optional[BaseException] = None) -> None:
        """The one terminal sequence for an admitted request: build the
        Completion from the slot, account it, evict, emit the record —
        ok and non-ok paths share it so the accounting can never
        desynchronize."""
        slot = self.pool.slots[idx]
        digest = None
        if error is not None:
            tb = traceback.format_exception(type(error), error,
                                            error.__traceback__)
            digest = f"{type(error).__name__}: {error}"
            tail = "".join(tb)[-2000:]
            digest = f"{digest}\n{tail}" if tail else digest
        comp = Completion(
            request=slot.request,
            tokens=slot.tokens[slot.n_prompt:],
            finish_reason=finish_reason,
            slot=idx,
            admitted_step=slot.admitted_step,
            finished_step=self.step_count,
            t_admitted=slot.t_admitted,
            t_first_token=slot.t_first_token,
            t_finish=now,
            status=status,
            error=digest)
        self.completions.append(comp)
        self.counts[status] += 1
        if self.slo is not None and status not in ("handoff", "migrated"):
            # A handoff/migration continues elsewhere — the destination
            # owns its terminal; scoring it here would double-count the
            # uid.
            self.slo.observe_request(
                status,
                ttft_ms=None if comp.ttft_s is None
                else comp.ttft_s * 1e3,
                tpot_ms=None if comp.tpot_s is None
                else comp.tpot_s * 1e3,
                queue_wait_ms=None if comp.queue_wait_s is None
                else comp.queue_wait_s * 1e3)
        self._trace_request(comp, slot_blocks=slot.n_mapped)
        self.pool.evict(idx)
        if self.sink is not None and status not in ("handoff", "migrated"):
            # A handoff's record is the kv_handoff _handoff_slot wrote,
            # a migration's the kv_migration extract_live wrote (the
            # request is continuing elsewhere, not failing here).
            record = request_complete_record if status == "ok" \
                else request_failed_record
            self.sink.write(record(comp, self.run_id,
                                   with_tenant=self.tag_tenants))

    def _terminal_unadmitted(self, req: Request, status: str,
                             pending: Optional[int] = None) -> None:
        """Terminate a never-admitted request: shed at arrival, expired
        in the queue, cancelled while queued, rejected as unservable at
        admission, or drained for requeueing (the drain record carries
        the requeued ids; shed gets its own record, with ``pending`` the
        tick's post-shed arrived backlog — computed once by the caller;
        timeout/cancelled/rejected ride ``request_failed``)."""
        now = time.perf_counter()
        comp = Completion(
            request=req, tokens=[], finish_reason=status, slot=-1,
            admitted_step=-1, finished_step=self.step_count,
            t_admitted=None, t_first_token=None, t_finish=now,
            status=status)
        self.completions.append(comp)
        self.counts[status] += 1
        if self.slo is not None:
            # Never admitted: no latencies to fold — still scored
            # (bad unless drained) so overload shows up in the burn.
            self.slo.observe_request(status)
        self._trace_request(comp)
        if self.sink is None:
            return
        if status == "shed":
            rec: Dict[str, Any] = {
                "record": "shed", "time": _wall(), "request_id": req.uid,
                "reason": "queue_full", "step": self.step_count,
                "pending": pending if pending is not None
                else self.queue.arrived_pending(self.step_count)}
            if self.queue.max_pending is not None:
                rec["max_pending"] = self.queue.max_pending
            if self.tag_tenants:
                rec["tenant"] = getattr(req, "tenant", "default")
            if self.run_id:
                rec["run_id"] = self.run_id
            self.sink.write(rec)
        elif status in ("timeout", "cancelled", "failed", "rejected"):
            self.sink.write(request_failed_record(
                comp, self.run_id,
                with_tenant=self.tag_tenants))
        # "drained": accounted by the serve_drain record, not per-request.

    # --------------------------------------------------------- handoff

    def _handoff_slot(self, idx: int, now: float) -> None:
        """Prefill-role terminal: gather slot ``idx``'s KV blocks into a
        :class:`~apex_example_tpu.serve.disagg.KvHandoff` (deep copy —
        COW-shared prefix blocks ship as payload bytes, never as
        references), emit the ``kv_handoff`` record (direction "out"),
        evict the slot with status "handoff" and push the payload into
        the transport.  Runs OUTSIDE the slot-isolation try like every
        terminal transition."""
        from apex_example_tpu.serve.disagg import KvHandoff
        slot = self.pool.slots[idx]
        req = slot.request
        fill, n_blocks, payload = self.pool.extract_blocks(idx)
        payload_bytes = sum(int(a.nbytes) for a in payload.values())
        # The REAL first-token latency is measurable only here, where
        # the first token was sampled — the decode side's timestamps
        # live in its own clock domain, so they ride the out record.
        ttft_ms = round((slot.t_first_token - req.t_arrival) * 1e3, 3) \
            if slot.t_first_token is not None else None
        queue_ms = round((slot.t_admitted - req.t_arrival) * 1e3, 3)
        handoff = KvHandoff(
            uid=req.uid, request=req, tokens=[int(t) for t in slot.tokens],
            fill=fill, block_size=self.pool.block_size,
            kv_dtype=self.pool.kv_dtype, payload=payload,
            payload_bytes=payload_bytes, t_out_wall=_wall(),
            src=self.role, ttft_ms=ttft_ms, queue_wait_ms=queue_ms)
        self._handoff_bytes += payload_bytes
        if self.sink is not None:
            rec: Dict[str, Any] = {
                "record": "kv_handoff", "time": _wall(),
                "request_id": req.uid, "direction": "out",
                "fill": fill, "blocks": n_blocks,
                "payload_bytes": payload_bytes,
                "kv_dtype": self.pool.kv_dtype,
                "prompt_tokens": len(req.prompt),
                "first_token": int(slot.tokens[-1]),
                "queue_wait_ms": queue_ms,
                "src": self.role}
            if ttft_ms is not None:
                rec["ttft_ms"] = ttft_ms
            if self.run_id:
                rec["run_id"] = self.run_id
            self.sink.write(rec)
        self._evict_terminal(idx, "handoff", "handoff", now)
        if self.tickprof is not None:
            # Spool IO attribution: the sink call is filesystem work
            # (serve/disagg.py spool write + fsync), not scheduler
            # cost — measured here, subtracted from harvest.
            t0 = time.perf_counter()
            self.handoff_sink(handoff)
            self._spool_ms += (time.perf_counter() - t0) * 1e3
        else:
            self.handoff_sink(handoff)

    def admit_handoff(self, handoff) -> bool:
        """Decode-role intake: admit a prefill worker's KV handoff into
        a slot, scattering its block payload into this engine's arena
        and resuming at ``cursor == fill`` with the first token already
        sampled.  Returns False — with NO state left behind — when a
        free slot or the worst-case block budget is missing right now:
        the caller requeues the same handoff deterministically and
        retries after evictions free capacity.  A handoff this engine
        could NEVER serve terminates first-class as "rejected" and
        returns True (consumed).  A handoff whose uid this engine
        ALREADY admitted — a redelivery of a claim that was never
        acked, or a duplicate delivery — is consumed idempotently: a
        ``kv_handoff`` record with ``duplicate: true`` lands, nothing
        is scattered, and True tells the caller to ack it."""
        if getattr(handoff, "kind", "handoff") == "migration":
            # Live-migration payloads (ISSUE 20) ride the same spool
            # and the same drive loops; dispatch here so every existing
            # poll -> admit -> ack caller works unchanged.
            return self.admit_migrated(handoff)
        req = handoff.request
        if req.uid in self.handoff_seen:
            # The ack-crash window closes here: admitted before, so the
            # payload (and possibly the finished request) already lives
            # in this engine — ack the redelivery, never scatter twice.
            self.handoff_duplicates += 1
            if self.sink is not None:
                rec: Dict[str, Any] = {
                    "record": "kv_handoff", "time": _wall(),
                    "request_id": req.uid, "direction": "in",
                    "fill": handoff.fill, "blocks": 0,
                    "payload_bytes": handoff.payload_bytes,
                    "kv_dtype": self.pool.kv_dtype,
                    "duplicate": True,
                    "redelivered": int(handoff.redelivered),
                    "dst": self.role}
                if self.run_id:
                    rec["run_id"] = self.run_id
                self.sink.write(rec)
            return True
        if self.draining:
            return False             # drain stopped admission (requeue)
        if handoff.block_size != self.pool.block_size:
            raise ValueError(
                f"handoff block_size {handoff.block_size} vs engine "
                f"{self.pool.block_size} — prefill and decode roles "
                "must share the arena geometry")
        if not self.pool.fits(req):
            self._terminal_unadmitted(req, "rejected")
            return True
        if not self.pool.can_admit_prefilled(req):
            if not handoff.requeued:
                # Counted once per handoff (an episode, not a retry
                # tally — the caller retries every tick and the wait
                # itself shows up in handoff_ms).
                handoff.requeued = 1
                self.handoff_requeued += 1
            return False
        now = time.perf_counter()
        idx = self.pool.admit_prefilled(req, self.step_count,
                                        handoff.fill, handoff.payload,
                                        handoff.tokens)
        slot = self.pool.slots[idx]
        slot.n_generated = len(handoff.tokens) - len(req.prompt)
        slot.t_first_token = now
        self.handoffs_in += 1
        self.handoff_seen.add(req.uid)
        if handoff.redelivered:
            self.handoff_redelivered.add(req.uid)
        self._handoff_bytes += handoff.payload_bytes
        transit_ms = max((_wall() - handoff.t_out_wall) * 1e3, 0.0)
        self._handoff_ms.append(transit_ms)
        if self._tracer is not None:
            self._rtrace[req.uid] = []
        if self.sink is not None:
            rec = {
                "record": "kv_handoff", "time": _wall(),
                "request_id": req.uid, "direction": "in",
                "fill": handoff.fill, "blocks": slot.n_mapped,
                "payload_bytes": handoff.payload_bytes,
                "kv_dtype": self.pool.kv_dtype,
                "prompt_tokens": len(req.prompt),
                "first_token": int(handoff.tokens[-1]),
                "handoff_ms": round(transit_ms, 3),
                "requeued": handoff.requeued,
                "dst": self.role}
            if handoff.redelivered:
                rec["redelivered"] = int(handoff.redelivered)
            if handoff.src:
                rec["src"] = handoff.src
            if self.run_id:
                rec["run_id"] = self.run_id
            self.sink.write(rec)
        return True

    # ------------------------------------------------------- migration

    def extract_live(self, uid: str):
        """Snapshot a MID-FLIGHT request into a migration payload
        (ISSUE 20): its arena blocks (storage-dtype-exact via
        extract_blocks — int8 payload + scales ship as-is), cursor,
        full token list, and sampler state (temperature / top_k ride
        the Request itself), evicting the slot with status "migrated"
        (outside the availability denominator — the destination owns
        the terminal).  Returns the :class:`KvHandoff` with
        ``kind="migration"`` for the caller to ship, or None when the
        uid holds no live slot.  Works at any point in the lifecycle:
        mid-prefill (fill < prompt length, zero generated tokens —
        the destination resumes the chunked prefill) as well as deep
        into decode.  ``admit_migrated`` resumes it token-identically
        under greedy sampling (temperature 0): the arena rows are
        bit-exact copies and argmax needs no RNG; sampled-temperature
        requests resume with the destination's stream."""
        for i in self.pool.live:
            if self.pool.slots[i].request.uid == uid:
                return self._migrate_slot(i, time.perf_counter())
        return None

    def _migrate_slot(self, idx: int, now: float):
        """Build one live slot's migration payload and evict it with
        status "migrated" — the live-migration counterpart of
        _handoff_slot.  Returns the payload; the CALLER ships it (drain
        passes its ``migrate`` callable; router-driven rebalance pushes
        straight into a transport)."""
        from apex_example_tpu.serve.disagg import KvHandoff
        pool = self.pool
        slot = pool.slots[idx]
        req = slot.request
        fill, n_mapped, payload = pool.extract_blocks(idx)
        BS = pool.block_size
        # The satellite bugfix (ISSUE 20): under --speculate,
        # stage_writes maps blocks for draft lanes the accept decision
        # then REJECTS — their rows are unverified garbage past the
        # committed cursor, and the cursor-rollback invariant (stale
        # rows hidden by the live mask until overwritten) only holds
        # inside this engine.  Ship exactly the blocks the cursor
        # covers; admit_prefilled allocates ceil(fill/BS) on the
        # destination and rejects a longer payload as malformed.
        n_ship = (fill + BS - 1) // BS
        if n_ship < n_mapped:
            payload = {k: v[:n_ship] for k, v in payload.items()}
        # Same invariant on the token list: everything past tokens[fill]
        # (the one pending next-feed token of a decoding slot) was never
        # verified against committed KV and must not resume elsewhere.
        tokens = [int(t) for t in slot.tokens]
        if not slot.prefilling:
            tokens = tokens[:fill + 1]
        payload_bytes = sum(int(a.nbytes) for a in payload.values())
        handoff = KvHandoff(
            uid=req.uid, request=req, tokens=tokens,
            fill=fill, block_size=BS,
            kv_dtype=pool.kv_dtype, payload=payload,
            payload_bytes=payload_bytes, t_out_wall=_wall(),
            src=self.role, kind="migration")
        self._migration_bytes += payload_bytes
        if self.sink is not None:
            rec: Dict[str, Any] = {
                "record": "kv_migration", "time": _wall(),
                "request_id": req.uid, "direction": "out",
                "fill": fill, "blocks": n_ship,
                "payload_bytes": payload_bytes,
                "kv_dtype": pool.kv_dtype,
                "prompt_tokens": len(req.prompt),
                "tokens_generated": slot.n_generated,
                "src": self.role}
            if self.tag_tenants:
                rec["tenant"] = getattr(req, "tenant", "default")
            if self.run_id:
                rec["run_id"] = self.run_id
            self.sink.write(rec)
        self._evict_terminal(idx, "migrated", "migrated", now)
        # The uid has LEFT this engine: a future payload for it (the
        # rebalance ping-pong, A -> B -> A) is a NEW incarnation, not a
        # duplicate delivery — suppression must forget it, or the
        # second visit would be acked-and-dropped (a lost request).
        self.handoff_seen.discard(req.uid)
        self.migration_redelivered.discard(req.uid)
        return handoff

    def admit_migrated(self, handoff) -> bool:
        """Resume a migrated mid-flight request (ISSUE 20): the intake
        twin of admit_handoff with the same contract — False with NO
        state left behind when a slot or the block budget is missing
        (the caller requeues and retries), True when consumed (admitted,
        rejected-as-unservable, or suppressed as a duplicate of a uid
        this engine already admitted).  Differences from the one-shot
        handoff path: the slot resumes with ``n_generated`` tokens
        already emitted (possibly zero — a mid-prefill migration keeps
        prefilling here), ``t_first_token`` is stamped only when the
        first token truly happened elsewhere, and the stream records
        are ``kv_migration``."""
        req = handoff.request
        if req.uid in self.handoff_seen:
            self.migration_duplicates += 1
            if self.sink is not None:
                rec: Dict[str, Any] = {
                    "record": "kv_migration", "time": _wall(),
                    "request_id": req.uid, "direction": "in",
                    "fill": handoff.fill, "blocks": 0,
                    "payload_bytes": handoff.payload_bytes,
                    "kv_dtype": self.pool.kv_dtype,
                    "duplicate": True,
                    "redelivered": int(handoff.redelivered),
                    "dst": self.role}
                if self.run_id:
                    rec["run_id"] = self.run_id
                self.sink.write(rec)
            return True
        if self.draining:
            return False             # drain stopped admission (requeue)
        if handoff.block_size != self.pool.block_size:
            raise ValueError(
                f"migration block_size {handoff.block_size} vs engine "
                f"{self.pool.block_size} — source and destination must "
                "share the arena geometry")
        if not self.pool.fits(req):
            self._terminal_unadmitted(req, "rejected")
            return True
        if not self.pool.can_admit_prefilled(req):
            if not handoff.requeued:
                handoff.requeued = 1
                self.migration_requeued += 1
            return False
        now = time.perf_counter()
        idx = self.pool.admit_prefilled(req, self.step_count,
                                        handoff.fill, handoff.payload,
                                        handoff.tokens)
        slot = self.pool.slots[idx]
        slot.n_generated = len(handoff.tokens) - len(req.prompt)
        if slot.n_generated > 0:
            # The first token was sampled on the SOURCE; stamping it at
            # admission keeps TTFT finite in this engine's clock domain
            # (the cross-domain truth rides the out record).  A
            # mid-prefill migration leaves it None — the first token
            # genuinely happens here.
            slot.t_first_token = now
        self.migrations_in += 1
        self.handoff_seen.add(req.uid)
        if handoff.redelivered:
            self.migration_redelivered.add(req.uid)
        self._migration_bytes += handoff.payload_bytes
        transit_ms = max((_wall() - handoff.t_out_wall) * 1e3, 0.0)
        self._migration_ms.append(transit_ms)
        if self._tracer is not None:
            self._rtrace[req.uid] = []
        if self.sink is not None:
            rec = {
                "record": "kv_migration", "time": _wall(),
                "request_id": req.uid, "direction": "in",
                "fill": handoff.fill, "blocks": slot.n_mapped,
                "payload_bytes": handoff.payload_bytes,
                "kv_dtype": self.pool.kv_dtype,
                "prompt_tokens": len(req.prompt),
                "tokens_generated": slot.n_generated,
                "migration_ms": round(transit_ms, 3),
                "requeued": handoff.requeued,
                "dst": self.role}
            if handoff.redelivered:
                rec["redelivered"] = int(handoff.redelivered)
            if handoff.src:
                rec["src"] = handoff.src
            if self.tag_tenants:
                rec["tenant"] = getattr(req, "tenant", "default")
            if self.run_id:
                rec["run_id"] = self.run_id
            self.sink.write(rec)
        return True

    # ----------------------------------------------------------- trace

    def _trace_request(self, comp: Completion,
                       slot_blocks: int = 0) -> None:
        """Emit one terminated request's lifecycle span tree (--trace):
        a root "request" span on its own ``req/<uid>`` row, with
        submit-handoff / queued / per-chunk prefill / decode child
        spans and first_token + terminal-status instants — every
        timestamp a ``perf_counter`` the request already accumulated on
        its way through, emitted in timestamp order at terminal time
        (obs/trace.py module docstring on why X-after-the-fact)."""
        tracer = self._tracer
        if tracer is None:
            return
        req = comp.request
        chunks = self._rtrace.pop(req.uid, [])
        t_arr = req.t_arrival
        t_sub = req.t_submit
        start = t_sub if t_sub is not None and t_sub < t_arr else t_arr
        tid = f"req/{req.uid}"
        args: Dict[str, Any] = {
            "request_id": req.uid, "status": comp.status,
            "prompt_tokens": len(req.prompt),
            "output_tokens": len(comp.tokens)}
        if comp.slot >= 0:
            args["slot"] = comp.slot
            args["admitted_tick"] = comp.admitted_step
            args["blocks"] = slot_blocks
            args["cow_copies"] = sum(1 for c in chunks if c[3])
        root = tracer.complete("request", start, comp.t_finish - start,
                               tid=tid, cat="request", args=args)
        if t_sub is not None and t_arr > t_sub:
            # loadgen -> queue handoff (Request.t_submit): client-side
            # latency the queue-wait metric must not absorb.
            tracer.complete("submit", t_sub, t_arr - t_sub, tid=tid,
                            cat="request", parent_id=root)
        q_end = comp.t_admitted if comp.t_admitted is not None \
            else comp.t_finish
        tracer.complete("queued", t_arr, q_end - t_arr, tid=tid,
                        cat="request", parent_id=root)
        for t0, t1, n_toks, cow in chunks:
            tracer.complete("prefill", t0, t1 - t0, tid=tid,
                            cat="request", parent_id=root,
                            args={"tokens": n_toks, "cow": cow})
        if comp.t_first_token is not None:
            tracer.instant("first_token", ts=comp.t_first_token,
                           tid=tid, parent_id=root)
            tracer.complete("decode", comp.t_first_token,
                            comp.t_finish - comp.t_first_token,
                            tid=tid, cat="request", parent_id=root)
        tracer.instant(comp.status, ts=comp.t_finish, tid=tid,
                       parent_id=root, args={"tick": comp.finished_step})

    # ------------------------------------------------------------ loop

    def run(self, max_steps: Optional[int] = None,
            idle_wait_s: float = 0.0, stop=None,
            on_tick=None) -> List[Completion]:
        """Drive ticks until the queue is drained and every slot is free
        (or ``max_steps`` ticks, or ``stop()`` — a callable the caller
        flips on SIGTERM to hand control to ``drain()``).
        ``idle_wait_s`` throttles idle spins when a producer thread
        feeds the queue in wall-clock time.  ``on_tick(engine)``, when
        given, runs after every tick (idle ticks included) — the
        replica-mode hook serve.py uses to flush its completion outbox
        and heartbeat without the engine knowing about either."""
        while max_steps is None or self.step_count < max_steps:
            if stop is not None and stop():
                break
            if self.work_drained() and not self.pool.any_live():
                break
            ran = self.step()
            if on_tick is not None:
                on_tick(self)
            if not ran and idle_wait_s:
                # v15 idle accounting: the sleep the summary used to
                # lose — idle_wait_ms measures what was actually slept
                # (the scheduler may overshoot idle_wait_s).
                t0 = time.perf_counter()
                time.sleep(idle_wait_s)
                self.idle_wait_ms += (time.perf_counter() - t0) * 1e3
        return self.completions

    # ----------------------------------------------------------- drain

    def drain(self, signal_name: str = "SIGTERM",
              migrate=None) -> Dict[str, Any]:
        """Graceful drain: stop admission, hand every still-queued
        request back with status "drained" (requeue-able elsewhere),
        then keep ticking until the in-flight slots finish or deadline-
        evict.  Returns (and emits, with a sink) the ``serve_drain``
        record; the caller then writes the normal, un-aborted
        ``serve_summary`` and exits ``EX_TEMPFAIL``.

        ``migrate`` (ISSUE 20) turns drain into drain-WITHOUT-eviction:
        a callable (typically ``transport.send``) each live slot's
        extract_live payload is pushed through instead of ticking the
        slot to completion — in-flight work leaves as "migrated"
        (resumed token-identically on a peer), zero ticks spent, zero
        deadline evictions, and the serve_drain record carries the
        ``migrated`` count."""
        self.draining = True
        drain_step = self.step_count
        if self._tracer is not None:
            # B/E (not X): the drain-phase ticks nest inside it on the
            # engine row, and a drain always runs to completion within
            # the bounded cap below, so the pair is balanced.
            self._tracer.begin("drain", tid="engine", cat="tick",
                               args={"signal": str(signal_name),
                                     "tick": drain_step})
        before = dict(self.counts)
        requeued = []
        if self.sched is not None:
            # Lane-parked requests drained the intake earlier, so they
            # arrived first — requeue them ahead of the intake backlog.
            requeued.extend(self.sched.drain())
        requeued.extend(self.queue.drain())
        for req in requeued:
            self._terminal_unadmitted(req, "drained")
        in_flight = len(self.pool.live)
        if migrate is not None:
            # Drain-without-eviction: ship every live slot MID-FLIGHT.
            # The loop below then sees no live slots — a migrating
            # drain spends zero decode ticks and can never deadline-
            # evict what it was asked to preserve.
            now = time.perf_counter()
            for i in list(self.pool.live):
                migrate(self._migrate_slot(i, now))
        # Bounded by construction: every live slot finishes within
        # max_len ticks (length cap) — the slack covers prefill already
        # under way.  A wedge here would be a bug, not load.
        cap = self.step_count + self.pool.max_len + 2
        while self.pool.any_live() and self.step_count < cap:
            self.step()
        rec: Dict[str, Any] = {
            "record": "serve_drain",
            "time": _wall(),
            "signal": str(signal_name),
            "step": drain_step,
            "in_flight": in_flight,
            "completed": self.counts["ok"] - before["ok"],
            "evicted": (self.counts["timeout"] - before["timeout"])
            + (self.counts["failed"] - before["failed"]),
            "requeued": len(requeued),
            "requeued_ids": [r.uid for r in requeued],
        }
        if migrate is not None:
            # Gated on the migrating drain (v18): a classic drain's
            # record stays byte-identical to pre-v18 output.
            rec["migrated"] = self.counts["migrated"] \
                - before["migrated"]
        if self.run_id:
            rec["run_id"] = self.run_id
        if self._tracer is not None:
            self._tracer.end("drain", tid="engine",
                             args={"completed": rec["completed"],
                                   "evicted": rec["evicted"],
                                   "requeued": rec["requeued"]})
        if self.sink is not None:
            self.sink.write(rec)
        return rec

    # --------------------------------------------------------- metrics

    def summary_record(self) -> Dict[str, Any]:
        """The ``serve_summary`` for everything terminated so far (the
        caller writes it to the sink and closes).  Schema v5 added
        per-status counts + the availability ratio (ok / every terminal
        status the server owned — drained requests are requeued
        elsewhere, so they sit outside the denominator); v7 adds the
        block-pool gauges (blocks_live / kv_bytes_committed /
        prefix_hit_rate / cow_copies) and makes ``kv_waste_pct``
        block-accurate: held-block bytes minus logically-live bytes,
        per compute tick — the dense layout's fixed full-page
        reservation measured ~92% here."""
        duration = time.perf_counter() - self._t0
        comps = self.completions
        ok = [c for c in comps if c.status == "ok"]
        # Drained, handed-off AND migrated requests continue elsewhere —
        # all three sit outside the availability denominator (v12/v18).
        owned = len(comps) - self.counts["drained"] \
            - self.counts["handoff"] - self.counts["migrated"]
        pool = self.pool
        rec: Dict[str, Any] = {
            "record": "serve_summary",
            "time": _wall(),
            "requests": len(comps),
            "output_tokens": self._tokens_out,
            "tokens_per_sec": round(self._tokens_out / max(duration, 1e-9),
                                    1),
            "steps": self.step_count,
            "compute_steps": self.compute_steps,
            "slots": pool.num_slots,
            "max_len": pool.max_len,
            "block_size": pool.block_size,
            "blocks_total": pool.num_blocks,
            "duration_s": round(duration, 3),
            "completed": self.counts["ok"],
            "timed_out": self.counts["timeout"],
            "shed": self.counts["shed"],
            "cancelled": self.counts["cancelled"],
            "failed": self.counts["failed"],
            "drained": self.counts["drained"],
            "rejected": self.counts["rejected"],
            "prefix_hit_rate": round(pool.prefix_hit_rate(), 4),
            "cow_copies": pool.cow_copies,
            "availability": round(self.counts["ok"] / owned, 3)
            if owned else 1.0,
            # v11 (ISSUE 13): the precision story — arena payload dtype,
            # weight storage mode, and the dtype-accurate vs
            # bf16-equivalent per-token costs the QUANT report line and
            # the ci_gate --quant-stream compression floor key on.
            "kv_dtype": pool.kv_dtype,
            "weight_dtype": _weight_dtype_name(self.weight_quant,
                                               self.params),
            "kv_bytes_per_token": pool.kv_bytes_per_token(),
            "kv_bytes_per_token_bf16": pool.kv_bytes_per_token_bf16(),
            # v12 (ISSUE 14): which part of the disaggregated topology
            # this engine played, and under which mesh.
            "role": self.role,
        }
        if self.mesh is not None:
            rec["mesh"] = f"data={self.dp},model={self.tp}"
            rec["dp"] = self.dp
            rec["tp"] = self.tp
        if self.counts["handoff"]:
            rec["handoffs_out"] = self.counts["handoff"]
        if self.handoffs_in:
            rec["handoffs_in"] = self.handoffs_in
        if self.handoff_requeued:
            rec["handoff_requeued"] = self.handoff_requeued
        if self.handoff_duplicates:
            rec["handoff_duplicates"] = self.handoff_duplicates
        if self.handoff_redelivered:
            rec["handoff_redelivered"] = len(self.handoff_redelivered)
        if self._handoff_bytes:
            rec["handoff_bytes"] = self._handoff_bytes
        if self._handoff_ms:
            rec["handoff_ms"] = _pct_dict(self._handoff_ms)
        # v18 (ISSUE 20): the live-migration ledger — every field gated
        # on actual migration traffic, so a migration-free stream stays
        # byte-identical to pre-v18 output.
        if self.counts["migrated"]:
            rec["migrations_out"] = self.counts["migrated"]
        if self.migrations_in:
            rec["migrations_in"] = self.migrations_in
        if self.migration_requeued:
            rec["migration_requeued"] = self.migration_requeued
        if self.migration_duplicates:
            rec["migration_duplicates"] = self.migration_duplicates
        if self.migration_redelivered:
            rec["migration_redelivered"] = len(self.migration_redelivered)
        if self._migration_bytes:
            rec["migration_bytes"] = self._migration_bytes
        if self._migration_ms:
            rec["migration_ms"] = _pct_dict(self._migration_ms)
        if self.compute_steps:
            rec["occupancy"] = round(
                self._occupancy_sum / (self.compute_steps
                                       * pool.num_slots), 3)
        # Arena-lifetime reservation (constant) + the per-tick block
        # gauges.  kv_waste_pct compares what the held blocks could
        # store against what live slots logically filled — the
        # block-rounding + reuse-lag overhead of the paged layout
        # (clamped at 0: heavy sharing counts shared tokens once
        # physically but once PER SLOT logically).
        reserved = pool.kv_bytes_reserved()
        rec["kv_bytes_reserved"] = reserved
        if self.compute_steps:
            kv = self._kv_hist.summary()
            blk = self._blk_hist.summary()
            rec["slot_occupancy"] = self._occ_hist.summary()
            rec["kv_bytes_live"] = kv
            rec["blocks_live"] = blk
            rec["kv_bytes_committed"] = self._committed_hist.summary()
            held = blk["mean"] * pool.block_size \
                * pool.kv_bytes_per_token()
            if held:
                rec["kv_waste_pct"] = round(
                    max(0.0, 100.0 * (1.0 - kv["mean"] / held)), 2)
        if ok:
            rec["ttft_ms"] = _pct_dict([c.ttft_s * 1e3 for c in ok])
            rec["tpot_ms"] = _pct_dict([c.tpot_s * 1e3 for c in ok])
            rec["queue_wait_ms"] = _pct_dict(
                [c.queue_wait_s * 1e3 for c in ok])
        if self.slo is not None:
            # v14 (ISSUE 16): score the trailing partial window first,
            # then embed the cumulative fold — spec, window/breach
            # totals, worst burn, sketch percentiles (the ci_gate
            # sketch-vs-exact check compares these against the exact
            # ttft_ms/tpot_ms dicts above).
            self.slo.flush()
            rec["slo"] = self.slo.summary()
        # v15 (ISSUE 17): idle-spin accounting (always on — a
        # producer-driven run's sleeps are no longer invisible) + the
        # cumulative host-overhead fraction when the profiler is armed.
        rec["idle_ticks"] = self.idle_ticks
        rec["idle_wait_ms"] = round(self.idle_wait_ms, 3)
        if self.tickprof is not None and self.tickprof.ticks:
            rec["host_overhead_frac"] = round(
                self.tickprof.host_overhead_frac(), 6)
        # v16 (ISSUE 18): the speculation ledger — emitted ONLY when
        # --speculate armed the engine, so an unarmed stream stays
        # byte-identical to pre-v16 output.  Conservation (ci_gate
        # --spec-stream): tokens_accepted <= tokens_drafted, and
        # output_tokens == tokens_accepted + tokens_sampled (every
        # emitted token is either a verified draft lane or a model
        # sample — the bonus lane and plain/sampled-path tokens).
        if self.speculate:
            rec["speculate_k"] = self.speculate
            rec["draft_kind"] = getattr(self.proposer, "name", "custom")
            rec["tokens_drafted"] = self.tokens_drafted
            rec["tokens_accepted"] = self.tokens_accepted
            rec["tokens_sampled"] = self.tokens_sampled
            rec["acceptance_rate"] = round(
                self.tokens_accepted / self.tokens_drafted, 4) \
                if self.tokens_drafted else 0.0
            if self.compute_steps:
                rec["tokens_per_tick"] = round(
                    self._tokens_out / self.compute_steps, 4)
        # v17 (ISSUE 19): the per-tenant scheduling ledger — emitted
        # ONLY when --tenants armed the fair scheduler, so an unarmed
        # stream stays byte-identical to pre-v17 output.  Each block
        # carries the DWRR config (weight / slo_class / budget), the
        # admitted-token debit total and the per-status terminal counts
        # (what ci_gate --tenant-stream conserves against the stream's
        # per-request records).
        if self.sched is not None:
            tenants = self.sched.summary()
            for c in comps:
                name = getattr(c.request, "tenant", "default")
                blk = tenants.setdefault(name, {
                    "weight": float(self.sched.spec(name).weight),
                    "slo_class": self.sched.spec(name).slo_class,
                    "admitted_tokens": 0, "queued": 0})
                counts = blk.setdefault("counts", {})
                counts[c.status] = counts.get(c.status, 0) + 1
            rec["tenants"] = tenants
        if self.run_id:
            rec["run_id"] = self.run_id
        return rec

    def slo_sketch(self) -> Optional[Dict[str, Any]]:
        """Compact serialized cumulative SLO sketches for a replica
        heartbeat (``replica_state.slo_sketch``); None without --slo."""
        return None if self.slo is None else self.slo.sketch_state()

    def host_overhead_frac(self) -> Optional[float]:
        """Cumulative (wall - device) / wall for a replica heartbeat
        (``replica_state.host_overhead_frac``); None without an armed
        --tick-profile profiler (or before its first compute tick)."""
        if self.tickprof is None or not self.tickprof.ticks:
            return None
        return self.tickprof.host_overhead_frac()

    # ---------------------------------------- scheduler-aware work view

    def unadmitted(self) -> int:
        """Requests waiting anywhere before admission: the intake queue
        PLUS the scheduler's lanes (v17 — with tenancy armed, lane
        residents have left ``queue.pending()``'s view but are very
        much still work)."""
        n = self.queue.pending()
        if self.sched is not None:
            n += self.sched.pending()
        return n

    def work_drained(self) -> bool:
        """True once no request can ever arrive or admit again: intake
        closed and empty, and (tenancy armed) every lane empty.  The
        run-loop exit test — ``queue.drained()`` alone would strand
        lane residents."""
        if not self.queue.drained():
            return False
        return self.sched is None or self.sched.pending() == 0

    def runnable_backlog(self) -> int:
        """Backlog that needs engine ticks RIGHT NOW: intake pops plus
        admissible lane work.  Budget-parked lanes count only once the
        intake is drained (a tick then finalizes them ``rejected``);
        behind an open intake they are NOT runnable — a drive loop
        with only parked work must idle-wait, not spin virtual time
        forward (which would race their virtual deadlines against
        host speed)."""
        n = self.queue.pending()
        if self.sched is not None:
            n += (self.sched.pending() if self.queue.drained()
                  else self.sched.admissible_pending())
        return n

    def tenant_admitted(self) -> Optional[Dict[str, int]]:
        """Per-tenant admitted-token totals for a replica heartbeat
        (``replica_state.tenant_admitted``); None unless tenancy is
        armed — unarmed heartbeats stay byte-identical."""
        if self.sched is None:
            return None
        return {name: tok
                for name, tok in self.sched.admitted_tokens.items()
                if tok}

    def prefix_advert(self) -> Optional[Dict[str, Any]]:
        """The prefix-cache advertisement for a replica heartbeat
        (``replica_state.prefix_keys`` + raw reuse counters); None
        unless ``--advertise-prefixes`` armed it."""
        if not self.advertise_prefixes:
            return None
        shared, total = self.pool.prefix_counters()
        return {
            "prefix_keys": self.pool.hot_prefix_hashes(
                self.advertise_prefixes),
            "prefix_shared_tokens": int(shared),
            "prefix_prompt_tokens": int(total),
        }
