"""Slot pool: a fixed-size request→row mapping over the shared KV cache.

The cache the pool owns is the model's own decode cache (flax 'cache'
collection under ``decode=True, slot_decode=True``): per layer,
``cached_key``/``cached_value`` pages of shape [SLOTS, max_len, H, D]
plus per-slot fill indices ([SLOTS] ``cache_index`` per layer and the
top-level [SLOTS] ``cache_position``).  A request is admitted by
resetting ONE row's indices to zero — the k/v pages are left untouched
(stale keys beyond the fill index are masked out by the per-slot live
mask inside attention, models/bert.py), so admit/evict costs O(1) index
writes, not an O(max_len·H·D) page clear.

The pool is host-side bookkeeping plus that one jitted index-reset; the
scheduler loop that feeds tokens through the slots lives in
serve/engine.py.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field
from typing import Any, List, Optional

import jax
import jax.numpy as jnp

from apex_example_tpu.serve.queue import Request

_INDEX_LEAVES = ("cache_index", "cache_position")
_PAGE_LEAVES = ("cached_key", "cached_value")


def _leaf_name(path) -> str:
    last = path[-1]
    return getattr(last, "key", getattr(last, "name", str(last)))


@jax.jit
def _reset_slot_indices(cache, slot):
    """Zero every per-slot index leaf at row ``slot`` (traced, so one
    compiled program serves every slot id)."""
    def reset(path, leaf):
        if _leaf_name(path) in _INDEX_LEAVES:
            return leaf.at[slot].set(0)
        return leaf
    return jax.tree_util.tree_map_with_path(reset, cache)


@dataclass
class Slot:
    """Host-side state of one live request in a slot.

    ``tokens`` is the full sequence (prompt + generated so far);
    ``cursor`` counts tokens already fed to the model.  Invariant during
    decode: ``len(tokens) == cursor + 1`` (the newest element is the next
    token to feed); during prefill ``cursor < n_prompt`` and generated
    output is still being discarded.
    """

    request: Request
    admitted_step: int
    t_admitted: float
    tokens: List[int] = field(default_factory=list)
    cursor: int = 0
    n_generated: int = 0
    t_first_token: Optional[float] = None

    @property
    def n_prompt(self) -> int:
        return len(self.request.prompt)

    @property
    def prefilling(self) -> bool:
        return self.cursor < self.n_prompt

    def next_token(self) -> int:
        return self.tokens[self.cursor]


class SlotPool:
    """``num_slots`` rows over one shared decode cache.

    ``model`` is the plain (training) GPT module; the pool derives the
    slot-decode clone and allocates the cache via an abstract init trace
    (no real forward runs), exactly like models/gpt.generate.
    """

    def __init__(self, model, num_slots: int, max_len: int):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if max_len < 2:
            raise ValueError(f"max_len must be >= 2, got {max_len}")
        if model.max_position < max_len:
            raise ValueError(f"max_len {max_len} exceeds the model's "
                             f"position table ({model.max_position})")
        self.dec = model.clone(decode=True, slot_decode=True,
                               fused_attention=False)
        self.num_slots = num_slots
        self.max_len = max_len
        shapes = jax.eval_shape(
            self.dec.init, jax.random.PRNGKey(0),
            jnp.zeros((num_slots, max_len), jnp.int32))["cache"]
        self.cache = jax.tree_util.tree_map(
            lambda t: jnp.zeros(t.shape, t.dtype), shapes)
        self.slots: List[Optional[Slot]] = [None] * num_slots
        self._free: List[int] = list(range(num_slots))[::-1]  # pop() = slot 0 first
        self._kv_reserved: Optional[int] = None

    # ------------------------------------------------------------ state

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def live(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is not None]

    def any_live(self) -> bool:
        return len(self._free) < self.num_slots

    # -------------------------------------------------------- lifecycle

    def admit(self, request: Request, step: int) -> int:
        """Insert ``request`` into a free slot: reset that row's cache
        indices and seed the host state.  Returns the slot id."""
        if not self._free:
            raise RuntimeError("no free slot (admission must check "
                               "free_count first)")
        n_prompt = len(request.prompt)
        if n_prompt >= self.max_len:
            raise ValueError(
                f"{request.uid}: prompt length {n_prompt} must be < "
                f"cache max_len {self.max_len}")
        idx = self._free.pop()
        self.cache = _reset_slot_indices(self.cache,
                                         jnp.asarray(idx, jnp.int32))
        self.slots[idx] = Slot(request=request, admitted_step=step,
                               t_admitted=time.perf_counter(),
                               tokens=[int(t) for t in request.prompt])
        return idx

    def evict(self, idx: int) -> None:
        """Free a slot (finished or cancelled).  The cache row keeps its
        stale contents; the next admit resets the indices."""
        if self.slots[idx] is None:
            raise RuntimeError(f"slot {idx} is already free")
        self.slots[idx] = None
        self._free.append(idx)

    def max_new_for(self, request: Request) -> int:
        """Effective output budget: the request's ask, clamped so the
        total sequence fits the cache row."""
        return min(request.max_new_tokens,
                   self.max_len - len(request.prompt))

    # ---------------------------------------------------- KV accounting

    def kv_bytes_reserved(self) -> int:
        """HBM bytes the dense KV pages pin for the engine's lifetime:
        every ``cached_key``/``cached_value`` leaf is a full
        [SLOTS, max_len, H, D] allocation regardless of what lives in
        it — the waste baseline a paged-KV refactor (ROADMAP item 2)
        gets scored against."""
        if self._kv_reserved is None:       # geometry is fixed; compute once
            total = 0
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                    self.cache)[0]:
                if _leaf_name(path) in _PAGE_LEAVES:
                    total += leaf.size * leaf.dtype.itemsize
            self._kv_reserved = total
        return self._kv_reserved

    def kv_bytes_per_token(self) -> int:
        """Bytes one cached token occupies across every layer's K and V
        page (``kv_bytes_reserved / (SLOTS * max_len)``) — multiply by a
        slot's fill level for its live footprint."""
        return self.kv_bytes_reserved() // (self.num_slots * self.max_len)

    def kv_bytes_live(self) -> int:
        """Bytes actually filled by the live slots (each slot's fed-token
        count times the per-token cost).  reserved - live = the HBM the
        dense layout wastes right now."""
        per_token = self.kv_bytes_per_token()
        return sum(s.cursor for s in self.slots if s is not None) \
            * per_token
