"""Block-paged KV cache: arena + free-list allocator + per-slot tables.

The dense layout this replaces pinned a [SLOTS, max_len, H, D] page per
slot, so HBM cost scaled with ``max_len`` regardless of request length
(PR 6's gauges measured ~92% ``kv_waste_pct`` on the smoke workload).
Here every layer owns ONE shared arena of shape
``[num_blocks, block_size, H, D]`` and a request maps only the blocks
its sequence actually touches, through a per-slot block table
(``[SLOTS, max_blocks]`` int32) the attention layers gather through
inside the one compiled decode step (models/bert.py).  Geometry stays
static — table CONTENTS are data, so the program still compiles exactly
once.

Host-side policy (this module, no jax in the allocator):

- **Free-list allocation** with per-block refcounts.  Admission
  reserves a request's worst-case block count up front
  (``ceil((prompt + max_new) / block_size)`` minus fully-shared
  blocks), so a decoding slot can never hit out-of-blocks mid-flight —
  OOM resolves deterministically at admission (queueing/shed in the
  engine), never as a stuck slot.
- **Prefix sharing** (copy-on-write): full blocks are registered in a
  chain-keyed index (each key hashes the block's tokens AND its whole
  prefix — KV content depends on every preceding token, so per-block
  content alone can never key it).  A new request maps the longest
  indexed chain covering its prompt, including a partial overlap into
  the last matched block; blocks mapped by several slots (or cached in
  the index) are immutable, and the first write into one triggers a
  block copy inside the compiled step (``cow_*`` pairs).  Zero-ref
  indexed blocks linger as a reusable cache (LRU-evicted under
  pressure), so a recurring system prompt keeps its KV across
  non-overlapping requests.
- **Chunked prefill**: the engine feeds up to ``block_size`` prompt
  tokens per tick through the same compiled step (serve/engine.py);
  this module's ``stage_writes``/``commit_writes`` bracket each tick's
  span with allocation/COW before and full-block registration after.

Shared prefixes always stop one token short of the full prompt: the
first generated token is sampled from the logits AFTER the last prompt
token, and sharing that position's KV would skip the forward pass that
produces those logits.
"""

from __future__ import annotations

import functools
import math
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_example_tpu.serve.queue import Request

# Arena payload leaves, and the scale tables that ride along under
# kv_quant (ISSUE 13) — accounting sums BOTH so the committed/live
# byte gauges stay honest about the quantized layout's true footprint.
_PAGE_LEAVES = ("cached_key", "cached_value")
_SCALE_LEAVES = ("cached_key_scale", "cached_value_scale")


def _leaf_name(path) -> str:
    last = path[-1]
    return getattr(last, "key", getattr(last, "name", str(last)))


def _path_str(path) -> str:
    """Stable string key for one cache leaf path — the identity KV
    handoff payloads are keyed by on both sides of the transport."""
    return "/".join(getattr(p, "key", getattr(p, "name", str(p)))
                    for p in path)


@functools.lru_cache(maxsize=8)
def _fused_block_scatter(shapes):
    """ONE jitted scatter writing a handoff payload into every arena
    leaf in a single dispatch (cached per geometry — ``shapes`` is the
    arena leaf shape tuple, so every admission at one geometry reuses
    one executable).  Out-of-range pad lanes drop."""
    del shapes                        # cache key only; shapes ride args

    @jax.jit
    def scatter(leaves, idx, rows):
        return tuple(l.at[idx].set(r, mode="drop")
                     for l, r in zip(leaves, rows))

    return scatter


@dataclass
class BlockNode:
    """One indexed (full, immutable) block: its chain key encodes the
    block's tokens and, through ``parent``, every token before it."""

    bid: int
    key: Tuple
    parent: Optional[Tuple]
    tokens: Tuple[int, ...]


class BlockAllocator:
    """Free-list + refcount + prefix-index bookkeeping for one arena.

    Pure host code (no jax): the engine calls it between compiled
    steps.  Determinism contract: allocation order, LRU eviction order
    and prefix-match tie-breaks depend only on the call sequence, so a
    rerun of the same request stream allocates identically.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.refcount = [0] * num_blocks
        self._immutable = [False] * num_blocks
        self._free: List[int] = list(range(num_blocks))[::-1]  # pop()=0 first
        # Zero-ref indexed blocks, LRU order (oldest first): reusable
        # prefix cache, evicted only when the free list runs dry.
        self._reusable: "OrderedDict[int, BlockNode]" = OrderedDict()
        self._index: Dict[Tuple, BlockNode] = {}
        self._children: Dict[Optional[Tuple], List[BlockNode]] = {}
        self._nodes: Dict[int, BlockNode] = {}   # bid -> node (indexed only)

    # ------------------------------------------------------------ state

    @property
    def blocks_in_use(self) -> int:
        """Blocks currently mapped by at least one slot."""
        return self.num_blocks - len(self._free) - len(self._reusable)

    def available(self, revive: Tuple[int, ...] = ()) -> int:
        """Blocks an admission could still draw from: the free list plus
        the evictable reusable cache, minus any of ``revive`` that sit
        in that cache (mapping a cached shared block removes it from the
        evictable pool, so it must not be double-counted)."""
        revived = sum(1 for b in set(revive) if b in self._reusable)
        return len(self._free) + len(self._reusable) - revived

    def immutable(self, bid: int) -> bool:
        return self._immutable[bid]

    # -------------------------------------------------------- lifecycle

    def alloc(self) -> int:
        """One fresh mutable block (refcount 1).  Draws the free list
        first, then evicts the least-recently-freed reusable block
        (deregistering its index entry).  Raising here means the
        caller's reservation accounting is broken — admission must have
        checked ``available()``."""
        if self._free:
            bid = self._free.pop()
        elif self._reusable:
            bid, node = self._reusable.popitem(last=False)
            self._deregister(node)
        else:
            raise RuntimeError(
                "out of KV blocks — admission reserves worst-case block "
                "budgets, so this is an allocator accounting bug")
        self.refcount[bid] = 1
        self._immutable[bid] = False
        return bid

    def ref(self, bid: int) -> None:
        """Map an already-cached block into one more slot (prefix
        sharing); revives it out of the reusable pool if parked there."""
        self.refcount[bid] += 1
        self._reusable.pop(bid, None)

    def unref(self, bid: int) -> None:
        """Drop one mapping.  At zero refs an indexed block parks in the
        reusable cache (its KV stays valid for future prefix hits); an
        unindexed one returns to the free list."""
        if self.refcount[bid] < 1:
            raise RuntimeError(f"unref of free block {bid}")
        self.refcount[bid] -= 1
        if self.refcount[bid] == 0:
            node = self._nodes.get(bid)
            if node is not None:
                self._reusable[bid] = node
            else:
                self._free.append(bid)

    def _deregister(self, node: BlockNode) -> None:
        del self._index[node.key]
        self._children[node.parent].remove(node)
        if not self._children[node.parent]:
            del self._children[node.parent]
        del self._nodes[node.bid]

    # ----------------------------------------------------- prefix index

    def register_full(self, parent: Optional[Tuple],
                      tokens: Tuple[int, ...], bid: int) -> Tuple:
        """Index a block that just filled (immutable from here on: any
        later write COWs).  A duplicate chain — two slots computed the
        same content in parallel — keeps the first index entry; the
        duplicate block stays unindexed and frees normally."""
        if len(tokens) != self.block_size:
            raise ValueError(f"register_full wants exactly "
                             f"{self.block_size} tokens, got {len(tokens)}")
        key = (parent, tokens)
        self._immutable[bid] = True
        if key not in self._index:
            node = BlockNode(bid, key, parent, tokens)
            self._index[key] = node
            self._children.setdefault(parent, []).append(node)
            self._nodes[bid] = node
        return key

    def match_prefix(self, prompt) -> Tuple[int, List[int], List[Tuple]]:
        """Longest cached prefix of ``prompt``: ``(shared_len, block
        ids, chain keys)``.  Walks exact full-block chain matches, then
        tries a partial overlap into one more indexed block (the COW
        case: the block is mapped read-only for its first few positions
        and copied at the first divergent write).  Read-only — the
        caller refs the returned blocks on admission.  Always capped at
        ``len(prompt) - 1`` so the last prompt token is re-fed (its
        forward pass produces the first sampled token's logits)."""
        BS = self.block_size
        bids: List[int] = []
        keys: List[Tuple] = []
        parent: Optional[Tuple] = None
        shared = 0
        for b in range(len(prompt) // BS):
            key = (parent, tuple(prompt[b * BS:(b + 1) * BS]))
            node = self._index.get(key)
            if node is None:
                break
            bids.append(node.bid)
            keys.append(key)
            parent = key
            shared += BS
        # Partial overlap into one more child block: first registered
        # child with the longest common prefix wins (deterministic).
        rest = tuple(prompt[shared:shared + BS])
        best, best_j = None, 0
        for node in self._children.get(parent, []):
            j = 0
            while j < len(rest) and node.tokens[j] == rest[j]:
                j += 1
            if j > best_j:
                best, best_j = node, j
        if best is not None:
            bids.append(best.bid)
            keys.append(best.key)
            shared += best_j
        shared = min(shared, len(prompt) - 1)
        n_mapped = math.ceil(shared / BS) if shared else 0
        return shared, bids[:n_mapped], keys[:n_mapped]

    def hot_prefixes(self, top_n: int) -> List[Tuple[int, ...]]:
        """The hottest indexed blocks' CUMULATIVE token prefixes,
        hottest first (ISSUE 19): rank every indexed block by live
        refcount (ties to lower bid — allocation order, deterministic)
        and unwind each chain key back to the full token prefix it
        covers.  Zero-ref blocks parked in the reusable cache rank
        last but still advertise — their KV is warm and a prefix hit
        revives them."""
        if top_n < 1:
            return []
        ranked = sorted(self._nodes.values(),
                        key=lambda n: (-self.refcount[n.bid], n.bid))
        out: List[Tuple[int, ...]] = []
        for node in ranked[:top_n]:
            parts: List[Tuple[int, ...]] = []
            key: Optional[Tuple] = node.key
            while key is not None:
                parent, toks = key
                parts.append(toks)
                key = parent
            out.append(tuple(t for toks in reversed(parts)
                             for t in toks))
        return out


@dataclass
class Slot:
    """Host-side state of one live request in a slot.

    ``tokens`` is the full sequence (prompt + generated so far);
    ``cursor`` counts tokens whose KV is in the arena — fed through the
    model OR covered by a shared prefix.  During decode
    ``len(tokens) == cursor + 1`` (the newest element is the next token
    to feed); during prefill ``cursor < n_prompt``.

    ``block_keys`` parallels the slot's mapped blocks: the chain key
    for registered (full, immutable) blocks, None for a mutable block
    still filling (registered by ``commit_writes`` when it fills).
    """

    request: Request
    admitted_step: int
    t_admitted: float
    tokens: List[int] = field(default_factory=list)
    cursor: int = 0
    n_generated: int = 0
    t_first_token: Optional[float] = None
    shared_len: int = 0
    n_mapped: int = 0
    reserved: int = 0
    block_keys: List[Optional[Tuple]] = field(default_factory=list)

    @property
    def n_prompt(self) -> int:
        return len(self.request.prompt)

    @property
    def prefilling(self) -> bool:
        return self.cursor < self.n_prompt


class BlockPool:
    """``num_slots`` request slots over one block-paged KV arena.

    ``model`` is the plain (training) GPT module; the pool derives the
    paged slot-decode clone and allocates the per-layer arenas via an
    abstract init trace (no real forward runs), exactly like
    models/gpt.generate.  ``num_blocks`` defaults to the dense
    layout's capacity (``num_slots * ceil(max_len / block_size)``), so
    the default arena reserves the same HBM the old [SLOTS, max_len]
    pages did — the win is that admission now shares and packs it.
    """

    def __init__(self, model, num_slots: int, max_len: int,
                 block_size: int = 8, num_blocks: Optional[int] = None,
                 kv_quant: bool = False, spec_slack: int = 0):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if max_len < 2:
            raise ValueError(f"max_len must be >= 2, got {max_len}")
        if spec_slack < 0:
            raise ValueError(f"spec_slack must be >= 0, got {spec_slack}")
        if model.max_position < max_len:
            raise ValueError(f"max_len {max_len} exceeds the model's "
                             f"position table ({model.max_position})")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.max_blocks = math.ceil(max_len / block_size)
        if num_blocks is None:
            num_blocks = num_slots * self.max_blocks
        self.num_slots = num_slots
        self.max_len = max_len
        self.block_size = block_size
        self.num_blocks = num_blocks
        # kv_quant (ISSUE 13): int8 arenas + bf16 per-token block
        # scales, quantize-on-scatter / dequant-in-gather inside the
        # same ONE compiled step (models/bert.py).  Allocation, COW
        # pairs and refcounts in this module are dtype-blind — only
        # the byte accounting below changes.
        self.kv_quant = bool(kv_quant)
        # spec_slack (ISSUE 18): with speculation armed, a slot's staged
        # write span can run up to K draft tokens ahead of its committed
        # cursor within a tick, so the worst-case reservation must cover
        # those in-flight positions or _alloc_for would fault mid-tick.
        self.spec_slack = int(spec_slack)
        self.dec = model.clone(decode=True, slot_decode=True,
                               fused_attention=False,
                               kv_num_blocks=num_blocks,
                               kv_block_size=block_size,
                               kv_quant=self.kv_quant)
        shapes = jax.eval_shape(
            self.dec.init, jax.random.PRNGKey(0),
            jnp.zeros((num_slots, max_len), jnp.int32))["cache"]
        self.cache = jax.tree_util.tree_map(
            lambda t: jnp.zeros(t.shape, t.dtype), shapes)
        self.alloc = BlockAllocator(num_blocks, block_size)
        self.table = np.zeros((num_slots, self.max_blocks), np.int32)
        self.slots: List[Optional[Slot]] = [None] * num_slots
        self._free: List[int] = list(range(num_slots))[::-1]  # pop()=slot 0
        self._reserved_total = 0
        self._kv_reserved: Optional[int] = None
        self.cow_copies = 0
        self._shared_tokens = 0
        self._prompt_tokens = 0
        self._mesh = None                    # set by shard(mesh)

    # --------------------------------------------------------- sharding

    def shard(self, mesh) -> None:
        """TP-shard the arenas over the mesh's ``model`` axis: every
        [NB, BS, H, D] payload leaf is placed head-sharded (the same
        layout the dense decode cache uses under TP), scale tables
        replicated.  The block tables, free list and admission logic
        stay host-side and replicated — sharding is a placement of the
        SAME geometry, so allocation/COW/refcount policy is untouched
        and the compiled step lowers once with GSPMD shardings."""
        self._mesh = mesh

        def put(path, leaf):
            return jax.device_put(leaf, self._leaf_sharding(path))

        self.cache = jax.tree_util.tree_map_with_path(put, self.cache)

    def _leaf_sharding(self, path):
        """The NamedSharding one cache leaf gets under the registered
        mesh: heads over 'model' for arena payloads, replicated for
        scale tables (and anything else)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from apex_example_tpu.parallel.mesh import MODEL_AXIS
        if _leaf_name(path) in _PAGE_LEAVES:
            return NamedSharding(self._mesh,
                                 P(None, None, MODEL_AXIS, None))
        return NamedSharding(self._mesh, P())

    # ------------------------------------------------------------ state

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def live(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is not None]

    def any_live(self) -> bool:
        return len(self._free) < self.num_slots

    # ---------------------------------------------------- block budgets

    def max_new_for(self, request: Request) -> int:
        """Effective output budget: the request's ask, clamped so the
        total sequence fits a slot's logical capacity."""
        return min(request.max_new_tokens,
                   self.max_len - len(request.prompt))

    def blocks_needed(self, request: Request,
                      shared_len: int = 0) -> int:
        """Worst-case blocks this request will ALLOCATE over its
        lifetime: blocks covering the clamped total sequence, minus
        fully-shared blocks (never written — a partially-overlapped
        shared block still costs its COW copy, so it is not
        subtracted).  With speculation armed, ``spec_slack`` extra
        in-flight tokens are budgeted: draft lanes stage KV writes up
        to K positions past the cursor before the accept decision."""
        total = len(request.prompt) + self.max_new_for(request) \
            + self.spec_slack
        return math.ceil(total / self.block_size) \
            - shared_len // self.block_size

    def fits(self, request: Request) -> bool:
        """Could this request EVER be admitted?  (Worst case, no
        sharing.)  False means admission must reject it outright —
        queueing would deadlock."""
        return self.max_new_for(request) >= 1 \
            and self.blocks_needed(request) <= self.num_blocks

    def can_admit(self, request: Request) -> bool:
        """Slot free AND the worst-case block budget (after prefix
        sharing) is coverable by unreserved blocks right now."""
        if not self._free:
            return False
        shared, bids, _ = self.alloc.match_prefix(request.prompt)
        need = self.blocks_needed(request, shared)
        return self.alloc.available(tuple(bids)) \
            - self._reserved_total >= need

    # -------------------------------------------------------- lifecycle

    def admit(self, request: Request, step: int) -> int:
        """Insert ``request`` into a free slot: map its shared prefix
        blocks (refcounted), reserve its worst-case allocation budget
        and seed the host state.  Returns the slot id.  The engine must
        gate on ``fits``/``can_admit`` first."""
        if not self._free:
            raise RuntimeError("no free slot (admission must check "
                               "free_count first)")
        n_prompt = len(request.prompt)
        if n_prompt >= self.max_len:
            raise ValueError(
                f"{request.uid}: prompt length {n_prompt} must be < "
                f"cache max_len {self.max_len} (admission should have "
                "rejected this request)")
        shared, bids, keys = self.alloc.match_prefix(request.prompt)
        need = self.blocks_needed(request, shared)
        idx = self._free.pop()
        for b in bids:
            self.alloc.ref(b)
        self.table[idx, :] = 0
        self.table[idx, :len(bids)] = bids
        self.slots[idx] = Slot(request=request, admitted_step=step,
                               t_admitted=time.perf_counter(),
                               tokens=[int(t) for t in request.prompt],
                               cursor=shared, shared_len=shared,
                               n_mapped=len(bids), reserved=need,
                               block_keys=list(keys))
        self._reserved_total += need
        self._shared_tokens += shared
        self._prompt_tokens += n_prompt
        return idx

    def evict(self, idx: int) -> None:
        """Free a slot (finished, failed or cancelled): unref its
        mapped blocks (full indexed ones park in the reusable prefix
        cache) and release the unspent reservation."""
        slot = self.slots[idx]
        if slot is None:
            raise RuntimeError(f"slot {idx} is already free")
        for b in range(slot.n_mapped):
            self.alloc.unref(int(self.table[idx, b]))
        self._reserved_total -= slot.reserved
        self.table[idx, :] = 0
        self.slots[idx] = None
        self._free.append(idx)

    # ------------------------------------------------------- KV handoff

    def extract_blocks(self, idx: int) -> Tuple[int, int, Dict[str, "np.ndarray"]]:
        """Gather slot ``idx``'s mapped arena blocks for a KV handoff:
        ``(fill, n_blocks, payload)`` where payload maps each arena
        leaf's path string to a host ``[n_blocks, BS, ...]`` array in
        the leaf's STORAGE dtype (int8 payload + bf16 scales under
        kv_quant — the handoff moves low-bit bytes, never dequantizes).

        The copy is deep by construction (``np.asarray`` of a device
        gather): a payload built from COW-shared prefix blocks shares
        nothing with the arena, so the receiver can never alias a
        block another request still maps."""
        slot = self.slots[idx]
        if slot is None:
            raise RuntimeError(f"slot {idx} is free — nothing to hand off")
        n = slot.n_mapped
        bids = jnp.asarray(np.ascontiguousarray(self.table[idx, :n]))
        payload: Dict[str, np.ndarray] = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                self.cache)[0]:
            if _leaf_name(path) in _PAGE_LEAVES + _SCALE_LEAVES:
                # np.array (not asarray): an OWNED writable host copy —
                # np.asarray of a jax array is a read-only view that
                # would pin the gather buffer across the transport.
                payload[_path_str(path)] = np.array(leaf[bids])
        return slot.cursor, n, payload

    def blocks_needed_prefilled(self, request: Request) -> int:
        """Worst-case blocks a handed-off request needs on the RECEIVING
        side: the full clamped sequence, no prefix sharing (the payload
        blocks are scattered fresh)."""
        return self.blocks_needed(request)

    def can_admit_prefilled(self, request: Request) -> bool:
        """Slot free AND the handed-off request's whole worst-case
        block budget is coverable right now.  The deterministic-requeue
        contract: a False here must leave NO state behind — the caller
        retries the same handoff later."""
        if not self._free:
            return False
        return self.alloc.available() - self._reserved_total \
            >= self.blocks_needed_prefilled(request)

    def admit_prefilled(self, request: Request, step: int, fill: int,
                        payload: Dict[str, "np.ndarray"],
                        tokens: List[int]) -> int:
        """Admit a request whose first ``fill`` tokens of KV arrive as a
        handoff payload: allocate the payload's blocks, scatter the
        rows into this pool's own arenas (dtype-checked — an int8
        payload must land in an int8 arena), seed the slot at
        ``cursor == fill`` and reserve the rest of the worst-case
        budget.  ``tokens`` is the full token list so far (prompt plus
        the prefill worker's first sampled token).  The caller gates on
        ``can_admit_prefilled`` first."""
        if not self._free:
            raise RuntimeError("no free slot (handoff admission must "
                               "check can_admit_prefilled first)")
        BS = self.block_size
        n_pay = math.ceil(fill / BS)
        total = self.blocks_needed_prefilled(request)
        if n_pay > total:
            raise ValueError(
                f"{request.uid}: payload covers {n_pay} blocks but the "
                f"clamped sequence only needs {total}")
        bids = [self.alloc.alloc() for _ in range(n_pay)]
        self._scatter_payload(bids, n_pay, payload)
        idx = self._free.pop()
        self.table[idx, :] = 0
        self.table[idx, :n_pay] = bids
        self.slots[idx] = Slot(request=request, admitted_step=step,
                               t_admitted=time.perf_counter(),
                               tokens=[int(t) for t in tokens],
                               cursor=fill, shared_len=0,
                               n_mapped=n_pay,
                               reserved=total - n_pay,
                               block_keys=[None] * n_pay)
        self._reserved_total += total - n_pay
        self._prompt_tokens += len(request.prompt)
        return idx

    def _scatter_payload(self, bids: List[int], n_pay: int,
                         payload: Dict[str, "np.ndarray"]) -> None:
        """Scatter handoff payload rows into this pool's arenas at the
        freshly allocated ``bids``.  Indices and rows are padded to
        ``max_blocks`` so ONE jitted scatter (all arena leaves fused
        into a single dispatch — admission latency sits inside the
        decode worker's TPOT window) serves every handoff size: pad
        lanes index row NB and drop.  Under a registered mesh the
        leaves are placed back on their arena shardings afterwards."""
        pad = max(self.max_blocks, n_pay)
        idx = np.full((pad,), self.num_blocks, np.int32)
        idx[:n_pay] = bids
        leaves, treedef = jax.tree_util.tree_flatten_with_path(self.cache)
        arena, rows_in, out = [], [], []
        for path, leaf in leaves:
            if _leaf_name(path) not in _PAGE_LEAVES + _SCALE_LEAVES:
                continue
            key = _path_str(path)
            if key not in payload:
                raise ValueError(
                    f"handoff payload missing arena leaf {key!r} — "
                    "prefill/decode geometry or kv_quant mismatch")
            rows = payload[key]
            if rows.shape[0] != n_pay or rows.shape[1:] != leaf.shape[1:]:
                raise ValueError(
                    f"handoff payload {key!r} shape {tuple(rows.shape)} "
                    f"does not fit arena {tuple(leaf.shape)} "
                    f"({n_pay} blocks)")
            if str(rows.dtype) != str(leaf.dtype):
                raise ValueError(
                    f"handoff payload {key!r} dtype {rows.dtype} vs "
                    f"arena {leaf.dtype} — the transport is "
                    "storage-dtype-exact (int8 stays int8)")
            padded = np.zeros((pad,) + tuple(rows.shape[1:]),
                              dtype=rows.dtype)
            padded[:n_pay] = rows
            arena.append(leaf)
            rows_in.append(padded)
        new = _fused_block_scatter(tuple(a.shape for a in arena))(
            tuple(arena), jnp.asarray(idx),
            tuple(jnp.asarray(r) for r in rows_in))
        it = iter(new)
        for path, leaf in leaves:
            if _leaf_name(path) in _PAGE_LEAVES + _SCALE_LEAVES:
                leaf = next(it)
                if self._mesh is not None:
                    leaf = jax.device_put(leaf,
                                          self._leaf_sharding(path))
            out.append(leaf)
        self.cache = jax.tree_util.tree_unflatten(treedef, out)

    def _alloc_for(self, slot: Slot) -> int:
        if slot.reserved < 1:
            raise RuntimeError(
                f"{slot.request.uid}: write past the reserved block "
                "budget — blocks_needed accounting bug")
        bid = self.alloc.alloc()
        slot.reserved -= 1
        self._reserved_total -= 1
        return bid

    def stage_writes(self, idx: int, n_new: int) -> Tuple[int, int]:
        """Pre-step: make every block covering write positions
        ``[cursor, cursor + n_new)`` mapped and mutable for slot
        ``idx``.  Returns the tick's COW pair ``(src, dst)`` —
        ``(-1, -1)`` when no shared block is written this tick.  At
        most one COW per slot per tick: only the first written block
        can be shared (later blocks in the span are freshly
        allocated)."""
        slot = self.slots[idx]
        cow = (-1, -1)
        start, end = slot.cursor, slot.cursor + n_new
        BS = self.block_size
        for b in range(start // BS, (end - 1) // BS + 1):
            if b < slot.n_mapped:
                bid = int(self.table[idx, b])
                if self.alloc.immutable(bid):
                    # First divergent write into a shared/cached block:
                    # copy-on-write inside the compiled step.
                    new = self._alloc_for(slot)
                    cow = (bid, new)
                    self.alloc.unref(bid)
                    self.table[idx, b] = new
                    slot.block_keys[b] = None     # content diverges
                    self.cow_copies += 1
            else:
                if b != slot.n_mapped:
                    raise RuntimeError("non-contiguous block mapping")
                self.table[idx, b] = self._alloc_for(slot)
                slot.block_keys.append(None)
                slot.n_mapped += 1
        return cow

    def commit_writes(self, idx: int, n_new: int) -> None:
        """Post-step: advance the slot's fill cursor and register every
        block that just became full in the prefix index (it turns
        immutable; its chain key hashes the whole token prefix)."""
        slot = self.slots[idx]
        slot.cursor += n_new
        BS = self.block_size
        for b in range(slot.n_mapped):
            if slot.block_keys[b] is None and (b + 1) * BS <= slot.cursor:
                parent = slot.block_keys[b - 1] if b else None
                toks = tuple(slot.tokens[b * BS:(b + 1) * BS])
                slot.block_keys[b] = self.alloc.register_full(
                    parent, toks, int(self.table[idx, b]))

    # ---------------------------------------------------- KV accounting

    def kv_bytes_reserved(self) -> int:
        """HBM bytes the arenas pin for the engine's lifetime: every
        ``cached_key``/``cached_value`` leaf is a full
        [num_blocks, block_size, H, D] allocation.  The default
        ``num_blocks`` makes this equal to the dense layout's
        reservation — the paged win shows up in the per-tick committed/
        live gauges, not here."""
        if self._kv_reserved is None:       # geometry is fixed; compute once
            total = 0
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                    self.cache)[0]:
                if _leaf_name(path) in _PAGE_LEAVES + _SCALE_LEAVES:
                    total += leaf.size * leaf.dtype.itemsize
            self._kv_reserved = total
        return self._kv_reserved

    def kv_bytes_per_token(self) -> int:
        """Bytes one cached token occupies across every layer's K and V
        arena (``kv_bytes_reserved / (num_blocks * block_size)``) —
        dtype-accurate: int8 payload plus the bf16 block scales under
        kv_quant, the full-precision payload otherwise."""
        return self.kv_bytes_reserved() \
            // (self.num_blocks * self.block_size)

    def kv_bytes_per_token_bf16(self) -> int:
        """What one cached token WOULD cost in a bf16 dense-payload
        arena of this geometry (2 bytes per K/V element, no scales) —
        the bf16-equivalent baseline the quant compression ratio and
        the ci_gate ``--quant-stream`` floor are computed against."""
        elems = 0
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                self.cache)[0]:
            if _leaf_name(path) in _PAGE_LEAVES:
                elems += leaf.size
        return elems * 2 // (self.num_blocks * self.block_size)

    @property
    def kv_dtype(self) -> str:
        """The arena payload dtype name ("int8" under kv_quant)."""
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                self.cache)[0]:
            if _leaf_name(path) in _PAGE_LEAVES:
                return str(leaf.dtype)
        return "none"                        # zero-layer model; untestable

    def kv_bytes_live(self) -> int:
        """Bytes of KV the live slots logically hold (per-slot fill
        level times the per-token cost; a shared block's tokens count
        once per sharer — this is the demand gauge, ``blocks_in_use``
        the physical one)."""
        per_token = self.kv_bytes_per_token()
        return sum(s.cursor for s in self.slots if s is not None) \
            * per_token

    def blocks_live(self) -> int:
        """Arena blocks physically held by live slots right now."""
        return self.alloc.blocks_in_use

    def blocks_committed(self) -> int:
        """Blocks admission has committed: physically held plus
        reserved-but-unallocated worst-case budget."""
        return self.alloc.blocks_in_use + self._reserved_total

    def prefix_hit_rate(self) -> float:
        """Shared prompt tokens / total prompt tokens over every
        admission so far (0.0 before any admission)."""
        if not self._prompt_tokens:
            return 0.0
        return self._shared_tokens / self._prompt_tokens

    def prefix_counters(self) -> Tuple[int, int]:
        """Raw ``(shared_tokens, prompt_tokens)`` behind the hit rate —
        what replicas advertise so the router can compute the EXACT
        fleet-level ratio (a mean of per-replica ratios would weight a
        one-request replica like a thousand-request one)."""
        return self._shared_tokens, self._prompt_tokens

    def hot_prefix_hashes(self, top_n: int) -> List[str]:
        """sched/prefix.py digests of the hottest indexed cumulative
        prefixes (ISSUE 19) — the replica_state advertisement the
        ``prefix_affinity`` router policy scores against."""
        from ..sched.prefix import hash_prefix
        return [hash_prefix(toks)
                for toks in self.alloc.hot_prefixes(top_n)]
