#!/usr/bin/env python
"""Accuracy-acceptance harness: the second half of the north star.

BASELINE.md's acceptance bar has two numbers — throughput (bench.py) AND
"<0.1% top-1 gap, amp-O2 bf16 vs fp32" (SURVEY.md §7).  This harness
measures the second: it trains the same model from the same init under two
opt levels on identical data, evaluates both on a held-out synthetic split,
and emits a JSON artifact:

    {"top1_fp32": ..., "top1_o2": ..., "gap": ..., ...}

Presets:
  ci    — ResNet-18 / CIFAR-shaped, few hundred steps, CPU-or-TPU (~min).
  full  — ResNet-50 / ImageNet-shaped on the real chip (long).

The train stream is ``image_batch(step)`` and the eval split lives at a
disjoint index range (indices >= 10^6), mirroring the train.py contract.
"""

from __future__ import annotations

import argparse
import json

import jax
import jax.numpy as jnp

from apex_example_tpu import amp
from apex_example_tpu.data import CIFAR10, IMAGENET, image_batch
from apex_example_tpu.engine import (create_train_state, make_eval_step,
                                     make_train_step)
from apex_example_tpu.models import ARCHS
from apex_example_tpu.obs import (FlightRecorder, JsonlSink, StallWatchdog,
                                  rank_print, span)
from apex_example_tpu.obs import metrics as obs_metrics
from apex_example_tpu.optim import FusedSGD, build_schedule

EVAL_OFFSET = 1_000_000     # held-out split: indices disjoint from training


def run_one(opt_level: str, arch: str, spec: dict, steps: int,
            batch_size: int, eval_batches: int, lr: float, warmup: int,
            seed: int, label_noise: float = 0.0,
            num_devices: int = 1) -> dict:
    policy, scaler = amp.initialize(opt_level)
    md = amp.module_dtypes(policy)
    model = ARCHS[arch](num_classes=spec["num_classes"],
                        dtype=md.compute, param_dtype=md.param,
                        bn_dtype=md.bn_stats, bn_io_dtype=md.bn_io,
                        bn_axis_name="data" if num_devices > 1 else None)
    schedule = build_schedule("cosine", lr, steps, warmup_steps=warmup)
    opt = FusedSGD(lr=schedule, momentum=0.9, weight_decay=5e-4)

    sample = jnp.zeros((1, spec["image_size"], spec["image_size"],
                        spec["channels"]), jnp.float32)
    state = create_train_state(jax.random.PRNGKey(seed), model, opt, sample,
                               policy, scaler)
    if num_devices > 1:
        from apex_example_tpu.engine import make_sharded_train_step
        from apex_example_tpu.parallel.mesh import make_data_mesh
        mesh = make_data_mesh(devices=jax.devices()[:num_devices])
        step_fn = make_sharded_train_step(mesh, model, opt, policy)
        eval_fn = jax.jit(make_eval_step(model))
    else:
        step_fn = jax.jit(make_train_step(model, opt, policy),
                          donate_argnums=(0,))
        eval_fn = jax.jit(make_eval_step(model))

    mk = lambda i: image_batch(jnp.asarray(i, jnp.int32),
                               batch_size=batch_size,
                               image_size=spec["image_size"],
                               channels=spec["channels"],
                               num_classes=spec["num_classes"], seed=seed,
                               label_noise=label_noise)
    with span("accuracy_train") as sp:
        for i in range(steps):
            state, metrics = step_fn(state, mk(i))
        final_loss = float(metrics["loss"])
    train_s = sp.dur_s

    # Full eval loop over the held-out split (top-1 averaged across batches;
    # every batch has the same size so the plain mean is exact).
    top1s, losses = [], []
    for j in range(eval_batches):
        em = eval_fn(state, mk(EVAL_OFFSET + j))
        top1s.append(float(em["top1"]))
        losses.append(float(em["loss"]))
    return {"opt_level": opt_level,
            "top1": sum(top1s) / len(top1s),
            "eval_loss": sum(losses) / len(losses),
            "final_train_loss": final_loss,
            "train_seconds": round(train_s, 1)}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="ci", choices=["ci", "full"])
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--batch-size", type=int, default=None)
    ap.add_argument("--eval-batches", type=int, default=None)
    ap.add_argument("--lr", type=float, default=None)
    ap.add_argument("--warmup-steps", type=int, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--seeds", default="",
                    help='comma seed list, e.g. "0,1,2" — runs every opt '
                         "level per seed and reports the gap mean ± spread "
                         "(overrides --seed)")
    ap.add_argument("--label-noise", type=float, default=None,
                    help="flip labels to a uniform class with this "
                         "probability: caps best top-1 at (1-p)+p/C so the "
                         "task cannot saturate and the fp32-vs-amp gap is "
                         "measured mid-range.  Default 0.3 (the noiseless "
                         "round-1/2 design saturated at 100/100 and "
                         "resolved nothing — see superseded/); pass 0 "
                         "explicitly for the saturating variant")
    ap.add_argument("--opt-levels", default="O0,O2")
    ap.add_argument("--platform", default="",
                    help="force a jax platform (e.g. 'cpu') before first "
                         "device use — the axon plugin otherwise pins the "
                         "real TPU even when the tunnel is down")
    ap.add_argument("--num-devices", type=int, default=1,
                    help=">1: DDP cells over a data mesh of this size")
    ap.add_argument("--out", default="ACCURACY.json")
    ap.add_argument("--metrics-jsonl", default="", metavar="PATH",
                    help="also emit one schema-valid 'accuracy' JSONL "
                         "record per (seed, opt level) cell as it lands "
                         "(obs/schema.py; tools/metrics_lint.py validates)")
    ap.add_argument("--flight-recorder", action="store_true",
                    help="with --metrics-jsonl: emit a 'crash_dump' "
                         "record on crash/SIGTERM (obs/flight.py)")
    ap.add_argument("--stall-timeout", type=float, default=0.0,
                    metavar="S",
                    help="with --metrics-jsonl: emit a 'stall' record "
                         "with thread stacks if no (seed, opt level) cell "
                         "completes for S seconds (0 disables; a cell "
                         "includes compile + its whole train loop — size "
                         "generously)")
    args = ap.parse_args(argv)
    if (args.flight_recorder or args.stall_timeout > 0) \
            and not args.metrics_jsonl:
        raise SystemExit("--flight-recorder/--stall-timeout write to the "
                         "telemetry sink; add --metrics-jsonl PATH")
    sink = JsonlSink(args.metrics_jsonl) if args.metrics_jsonl else None
    recorder = watchdog = None
    if sink is not None and args.flight_recorder:
        recorder = FlightRecorder(sink=sink, config=vars(args))
        recorder.install()
    if sink is not None and args.stall_timeout > 0:
        watchdog = StallWatchdog(sink, deadline_s=args.stall_timeout)
        watchdog.start()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    if args.preset == "ci":
        arch, spec = "resnet18", CIFAR10
        defaults = dict(steps=300, batch_size=128, eval_batches=8, lr=0.1,
                        warmup=20)
    else:
        # eval 32×256 = 8192 examples => top-1 quantum 0.0122% — far under
        # the 0.1% acceptance bar (VERDICT r3: a quantum EQUAL to the bar
        # proves nothing).
        arch, spec = "resnet50", IMAGENET
        defaults = dict(steps=1500, batch_size=256, eval_batches=32, lr=0.2,
                        warmup=100)
    if args.label_noise is None:
        args.label_noise = 0.3
    steps = args.steps if args.steps is not None else defaults["steps"]
    bs = args.batch_size if args.batch_size is not None \
        else defaults["batch_size"]
    ev = args.eval_batches if args.eval_batches is not None \
        else defaults["eval_batches"]
    lr = args.lr if args.lr is not None else defaults["lr"]
    warmup = args.warmup_steps if args.warmup_steps is not None \
        else defaults["warmup"]

    seeds = [int(s) for s in args.seeds.split(",") if s.strip()] \
        or [args.seed]
    levels = [lvl.strip() for lvl in args.opt_levels.split(",")]
    per_seed = {}
    cells = 0
    # NOTE: no try/finally here — on an uncaught exception the flight
    # recorder's sys.excepthook backstop writes the crash_dump (nothing
    # in between closes the sink), and the watchdog thread is a daemon.
    for seed in seeds:
        results = {}
        for lvl in levels:
            r = run_one(lvl, arch, spec, steps, bs, ev, lr, warmup, seed,
                        label_noise=args.label_noise,
                        num_devices=args.num_devices)
            results[lvl] = r
            cells += 1
            if watchdog is not None:
                watchdog.notify_step(cells)
            rank_print(f"seed {seed} {lvl}: top1 {r['top1']:.2f}%  "
                       f"eval_loss {r['eval_loss']:.4f}  "
                       f"({r['train_seconds']}s)")
            if sink is not None:
                sink.write({"record": "accuracy",
                            "time": obs_metrics.now(), "seed": seed, **r})
        per_seed[seed] = results

    l0, l1 = (levels + levels)[:2]
    gaps = [per_seed[s][l0]["top1"] - per_seed[s][l1]["top1"]
            for s in seeds] if len(levels) >= 2 else []
    mean = lambda xs: sum(xs) / len(xs)
    artifact = {
        "preset": args.preset, "arch": arch, "steps": steps,
        "batch_size": bs, "eval_batches": ev,
        # The smallest top-1 step the eval set can resolve (one example
        # flipping).  A credible "<0.1% gap" verdict needs quantum << 0.1
        # (VERDICT r3: 1024 eval examples made the quantum EQUAL the bar).
        "top1_quantum_pct": 100.0 / (ev * bs),
        "label_noise": args.label_noise, "seeds": seeds,
        "top1_fp32": mean([per_seed[s]["O0"]["top1"] for s in seeds])
        if "O0" in levels else None,
        "top1_o2": mean([per_seed[s]["O2"]["top1"] for s in seeds])
        if "O2" in levels else None,
        "per_seed": {str(s): per_seed[s] for s in seeds},
    }
    if args.label_noise:
        artifact["top1_ceiling"] = 100.0 * (
            1.0 - args.label_noise
            + args.label_noise / spec["num_classes"])
    if gaps:
        artifact["gap"] = mean(gaps)
        artifact["gap_per_seed"] = gaps
        artifact["gap_spread"] = max(gaps) - min(gaps)
        rank_print(f"top-1 gap ({l0} − {l1}): {artifact['gap']:+.3f}% "
                   f"(per-seed {['%+.3f' % g for g in gaps]}, spread "
                   f"{artifact['gap_spread']:.3f}; acceptance: |gap| < 0.1% "
                   f"at convergence)")
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1)
    if watchdog is not None:
        watchdog.close()
    if recorder is not None:
        recorder.close()
    if sink is not None:
        sink.close()
    rank_print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
